"""Fault-tolerant metric sync: deadlines, retry/backoff, quorum degradation.

The reference toolkit assumes every rank is alive: one dead or slow host
makes every collective in a ``sync_and_compute`` hang forever (reference
toolkit.py:206-260 has no timeout surface at all), turning a cheap metrics
sync into a pod-wide outage. Fault-tolerant collective stacks treat peer
loss as a *recoverable event* instead — the Prime Collective Communications
Library (arxiv 2505.14065) degrades to the surviving peers, and EQuARX
(arxiv 2506.17615) shows the collective layer itself is a legitimate place
to intervene. This module brings that posture to the metric sync path:

- :class:`ResilientGroup` decorates any ``ProcessGroup`` (``MultiHostGroup``,
  ``LocalReplicaGroup``, test fakes) with **per-collective deadlines** (the
  gather runs on a reusable worker thread; the caller's wait is bounded),
  **retry with exponential backoff + deterministic jitter** for transient
  failures, and a configurable **degradation policy**:

  - ``"raise"``  — today's behavior, except a bounded, *typed*
    :class:`SyncTimeoutError` instead of an unbounded hang;
  - ``"local"``  — fall back to this rank's unsynced state; the merged
    result is flagged stale via its sync provenance;
  - ``"quorum"`` — merge the ranks that did respond, provided at least
    ``quorum`` (a fraction of world size) arrived.

- :class:`SyncHealth` is the observability record (attempts, retries,
  timeouts, corrupt payloads, last good sync, participating ranks, reform
  events) exposed on every ``ResilientGroup`` — the sync-path sibling of
  ``utils.CompileCounter``.

- **Survivor re-formation** (persistent-failure escalation, PCCL's peer
  eviction as a metrics-layer policy): with ``reform_after=N`` (or
  ``config.sync_reform_after()``), ``N`` consecutive quorum-degraded syncs
  missing the SAME ranks re-form the group onto a survivors-only subgroup
  (``new_subgroup``) — later syncs run full-speed and undegraded instead
  of paying the deadline/quorum machinery for a rank that stays dead
  forever. Reform events land in :class:`SyncHealth` and are stamped into
  every subsequent :class:`SyncProvenance` (``reformed=True``).

The happy path adds **zero extra collectives** (pinned by
``tests/metrics/test_sync_collective_counts.py``): the wrapper forwards each
gather exactly once, and the partial-participation metadata rides the
metadata exchange the protocol already pays for
(``synclib.sync_states``).

Partial gathers: a fault-aware inner group (production: a PCCL-style
collective; tests: ``utils.test_utils.FaultInjectionGroup``) signals peer
loss by raising :class:`PartialGatherError` carrying the payloads of the
ranks that DID respond. A plain timeout yields no partial data: the
surviving set is then just this rank.

See docs/fault-tolerance.md for the policy walkthroughs.
"""

from __future__ import annotations

import math
import queue
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from torcheval_tpu.distributed import LocalReplicaGroup, ProcessGroup
from torcheval_tpu.obs import flight as _flight
from torcheval_tpu.obs.flight import FLIGHT as _FLIGHT
from torcheval_tpu.obs.recorder import RECORDER as _OBS

__all__ = [
    "PartialGatherError",
    "ResilientGroup",
    "SyncHealth",
    "SyncIntegrityError",
    "SyncProvenance",
    "SyncTimeoutError",
    "TransientSyncError",
    "backoff_delay",
    "bounded_call",
    "default_sync_health",
]

# A degrading policy is a promise that a dead host costs a bounded wait:
# without a deadline a plain (non-fault-aware) group would still hang
# forever, so groups constructed with policy != "raise" and no explicit
# timeout get this default deadline per collective.
DEFAULT_DEGRADING_TIMEOUT = 300.0

# Health of every config-driven (auto-wrapped) sync: those wrappers are
# constructed per toolkit call, so their counters would be unreachable and
# reset every sync without a process-wide record to accumulate into.
_DEFAULT_HEALTH = None


def default_sync_health() -> "SyncHealth":
    """The process-wide :class:`SyncHealth` accumulated by every
    config-driven sync (toolkit calls under ``config.sync_resilience`` /
    env knobs / ``on_failure=``, where the caller never holds the group
    object). Explicitly constructed ``ResilientGroup``s keep their own."""
    global _DEFAULT_HEALTH
    if _DEFAULT_HEALTH is None:
        _DEFAULT_HEALTH = SyncHealth()
    return _DEFAULT_HEALTH


class SyncTimeoutError(RuntimeError):
    """A metric-sync collective missed its deadline (or lost too many peers
    to satisfy the degradation policy) after all retries."""


class TransientSyncError(RuntimeError):
    """A retryable wire glitch (the inner group believes the next attempt
    may succeed). ``ResilientGroup`` retries these with backoff."""


class SyncIntegrityError(RuntimeError):
    """A gathered payload failed its checksum (rides the metadata exchange
    — see ``synclib.sync_states``). Raised under the ``raise`` policy;
    degrading policies drop the corrupt rank instead."""


class PartialGatherError(RuntimeError):
    """A fault-aware collective completed for only a subset of ranks.

    ``values`` maps rank -> that rank's payload for every rank that DID
    respond. ``ResilientGroup`` turns this into a quorum merge (policy
    ``"quorum"``), a local fallback (``"local"``), or a
    :class:`SyncTimeoutError` (``"raise"``).

    CONTRACT for inner groups raising this: every surviving rank must be
    told the SAME survivor set (fault-tolerant collective stacks provide
    this via consensus-based membership — PCCL, arxiv 2505.14065 §3).
    Divergent per-rank survivor sets would make ranks pad the follow-up
    payload gather to different static shapes and merge different state
    (split-brain); this layer consumes the membership decision, it does
    not arbitrate one.
    """

    def __init__(self, message: str, values: Dict[int, Any]) -> None:
        super().__init__(message)
        self.values = dict(values)


class SyncProvenance(NamedTuple):
    """Which ranks contributed to a synced result (attached to metrics
    returned by ``toolkit.get_synced_metric(_collection)`` as
    ``metric.sync_provenance``).

    The staleness triple (``version``/``rounds_behind``/
    ``wall_age_seconds``) mirrors the per-region vocabulary of
    :class:`torcheval_tpu.federation.FederationProvenance` so
    intra-region and WAN reads speak ONE staleness model. Blocking syncs
    are by definition fresh (the defaults); bounded-staleness reads off a
    :class:`torcheval_tpu.syncplane.SyncPlane` stamp the snapshot's
    merge version, how many publish generations the serving state has
    advanced past it, and its wall age.
    """

    ranks: Tuple[int, ...]
    world_size: int
    degraded: bool  # True when ranks != all of world (result may be stale)
    policy: str
    # True once the group has re-formed onto a survivors-only subgroup
    # (persistent-failure escalation): ranks/world_size are then relative
    # to the REFORMED subgroup — map to global ranks via ``group.ranks``.
    reformed: bool = False
    # bounded-staleness triple (syncplane reads; federation regions carry
    # the same fields per region in FederationProvenance):
    version: int = 0  # plane merge version this read observed (0 = blocking)
    rounds_behind: int = 0  # publish generations newer than this version
    wall_age_seconds: float = 0.0  # age of the merged snapshot at read time
    # admission-control triple (appended-defaulted-field discipline, like
    # the staleness triple above): a metric table armed with an
    # :class:`torcheval_tpu.table.AdmissionController` stamps the ladder
    # rung its merged state was ingested under, so every consumer of a
    # synced value can see whether it reflects full ingest or a sampled /
    # shedding regime (Horvitz-Thompson reweighted — aggregates stay
    # unbiased, but variance grows as ``sampled_fraction`` shrinks).
    # Defaults read "full ingest" for every non-table / unarmed metric.
    sampled_fraction: float = 1.0  # Bernoulli keep probability at this rung
    admission_rung: int = 0  # 0=full 1=sampled 2=priority-shed
    admission_epoch: int = 0  # drain epoch the rung last changed
    # quantized-wire-ladder rung the synced payload ACTUALLY rode
    # (torcheval_tpu/wire.py; appended-defaulted like the triples above
    # so legacy positional construction keeps working): "exact" |
    # "bf16" | "int8" — the lossiest encoding any surviving rank
    # applied to this metric's states. "exact" means bit-exact wire,
    # including when a lossy policy was configured but every payload
    # stayed raw/sparse (integer counters, tiny states).
    wire_tier: str = "exact"
    # rank-loss declaration (appended-defaulted like the fields above):
    # a :class:`torcheval_tpu.failover.LossBound` once a FailureDomain
    # recovery rebuilt state after losing ranks — steps/epochs of the
    # dead ranks' updates that were unrecoverable since the committed
    # generation the reconstruction drew from. ``None`` means no rank
    # was ever lost; a bound with ``exact=True`` means ranks WERE lost
    # but the kill landed on a generation boundary and nothing is
    # missing. The bound is permanent: post-recovery drains re-stamp it
    # (FailureDomain.stamp), so every later compute() carries honest
    # loss provenance. Typed ``Any`` to keep this module free of a
    # failover import; the value is always None or a LossBound.
    loss: Any = None


@dataclass
class SyncHealth:
    """Running observability record for one ``ResilientGroup``.

    Counters accumulate over the group's lifetime; ``participating_ranks``
    and ``last_good_sync`` reflect the most recent sync. Read it off
    ``group.health`` next to PR 1's compile observability
    (``utils.CompileCounter``) when deciding whether degraded metrics are
    trustworthy.
    """

    attempts: int = 0  # tev: guarded-by=_lock
    retries: int = 0  # tev: guarded-by=_lock
    timeouts: int = 0  # tev: guarded-by=_lock
    transient_errors: int = 0  # tev: guarded-by=_lock
    partial_gathers: int = 0  # tev: guarded-by=_lock
    corrupt_payloads: int = 0  # tev: guarded-by=_lock
    degraded_syncs: int = 0  # tev: guarded-by=_lock
    full_syncs: int = 0  # tev: guarded-by=_lock
    last_good_sync: Optional[float] = None  # tev: guarded-by=_lock
    participating_ranks: Tuple[int, ...] = ()  # tev: guarded-by=_lock
    world_size: int = 0
    policy: str = "raise"
    # survivor re-formation (persistent-failure escalation)
    reforms: int = 0  # tev: guarded-by=_lock
    reformed_to: Tuple[int, ...] = ()  # tev: guarded-by=_lock
    consecutive_missing: Tuple[int, ...] = ()  # tev: guarded-by=_lock
    consecutive_missing_count: int = 0  # tev: guarded-by=_lock
    _lock: threading.Lock = field(
        default_factory=threading.Lock, repr=False, compare=False
    )

    def as_dict(self) -> Dict[str, Any]:
        # one consistent snapshot: readers used to see e.g. a bumped
        # `attempts` next to a not-yet-bumped `timeouts` mid-update
        # (caught by the ISSUE 15 guarded-field sweep; pinned by
        # tests/test_utils/test_schedule.py::test_sync_health_as_dict_is_torn_free)
        with self._lock:
            return {
                "attempts": self.attempts,
                "retries": self.retries,
                "timeouts": self.timeouts,
                "transient_errors": self.transient_errors,
                "partial_gathers": self.partial_gathers,
                "corrupt_payloads": self.corrupt_payloads,
                "degraded_syncs": self.degraded_syncs,
                "full_syncs": self.full_syncs,
                "last_good_sync": self.last_good_sync,
                "participating_ranks": list(self.participating_ranks),
                "world_size": self.world_size,
                "policy": self.policy,
                "reforms": self.reforms,
                "reformed_to": list(self.reformed_to),
                "consecutive_missing": list(self.consecutive_missing),
                "consecutive_missing_count": self.consecutive_missing_count,
            }


class _SyncWorker:
    """One reusable DAEMON worker thread running collective attempts.

    Deliberately not ``concurrent.futures``: its pools register an atexit
    join of every (non-daemon) worker, so a thread still blocked inside a
    dead host's collective would hang interpreter exit — re-creating at
    shutdown exactly the hang the deadline exists to prevent. A daemon
    loop thread dies with the process, and reusing it keeps the happy-path
    cost to one queue hop (~tens of µs).
    """

    def __init__(self) -> None:
        self._jobs: "queue.SimpleQueue" = queue.SimpleQueue()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="torcheval-sync"
        )
        self._thread.start()

    def _loop(self) -> None:  # tev: scope=worker
        while True:
            job = self._jobs.get()
            if job is None:  # stop sentinel: surplus reclaimed worker
                return
            fn, box, done = job
            try:
                box["value"] = fn()
            except BaseException as e:  # noqa: BLE001 — ferried to caller
                box["error"] = e
            done.set()

    def stop(self) -> None:
        self._jobs.put(None)

    def submit(
        self, fn: Callable[[], Any]
    ) -> Tuple[Dict[str, Any], threading.Event]:
        box: Dict[str, Any] = {}
        done = threading.Event()
        self._jobs.put((fn, box, done))
        return box, done


def _harvest(box: Dict[str, Any]) -> Any:
    if "error" in box:
        raise box["error"]
    return box["value"]


# ONE worker per CALLER THREAD, shared by every ResilientGroup that thread
# drives: the sync path is caller-serial PER THREAD, and a per-group worker
# would leak one never-exiting daemon thread per auto-wrapped toolkit call
# (config-driven wrapping constructs a fresh group per sync). Thread-local,
# not process-global: concurrent caller threads (a multi-threaded eval
# driver, ThreadWorld rank emulation) are independent collective sequences
# — serializing them through one worker would deadlock rendezvousing
# collectives, and one thread's straggler must not fence another thread's
# healthy sync. A timed-out worker is poisoned for its thread — its thread
# is stuck inside the abandoned collective — and the next call creates a
# replacement.
_TLS = threading.local()


class _WorkerBox(list):
    """1-slot box holding a caller thread's idle reusable worker.

    When the caller thread dies, its thread-local storage is released and
    this box is garbage-collected: stop the idle worker then, so each
    exiting caller thread does not leave a permanently-parked
    'torcheval-sync' daemon behind.
    """

    def __del__(self) -> None:
        worker = self[0] if self else None
        if worker is not None:
            try:
                worker.stop()
            except Exception:  # noqa: BLE001 — interpreter teardown
                pass


class _InFlightList(list):
    """A caller thread's abandoned (done event, worker) attempts.

    On the caller thread's exit this list is GC'd: enqueue each worker's
    stop sentinel so a straggler whose collective eventually LANDS drains
    the sentinel next and exits, instead of parking in ``_jobs.get()``
    forever (the process-global design reclaimed these from any thread;
    thread-local state must reclaim them at teardown). A worker stuck in
    a never-returning collective stays stuck — unreclaimable by
    construction, it dies with the process, same as before.
    """

    def __del__(self) -> None:
        for _done, worker in self:
            try:
                worker.stop()
            except Exception:  # noqa: BLE001 — interpreter teardown
                pass


def _tls_state() -> Tuple[List[Optional[_SyncWorker]], list]:
    """This caller thread's (shared-worker box, in-flight list)."""
    if not hasattr(_TLS, "worker_box"):
        _TLS.worker_box = _WorkerBox([None])
        # abandoned attempts still in flight — PER-THREAD but surviving
        # group objects (config-driven wrapping constructs a fresh
        # ResilientGroup per sync), so it cannot live on the group
        _TLS.in_flight = _InFlightList()
    return _TLS.worker_box, _TLS.in_flight


def _reclaim_finished() -> None:
    """Recycle workers whose abandoned attempt has since completed: one is
    reinstated as the shared worker, surplus ones are stopped — a
    deadline miss whose collective lands late must not leak a thread."""
    box, in_flight = _tls_state()
    pending = []
    for done, worker in in_flight:
        if not done.is_set():
            pending.append((done, worker))
        elif box[0] is None:
            box[0] = worker  # idle again: back to work
        else:
            worker.stop()
    in_flight[:] = pending


def _get_worker() -> _SyncWorker:
    _reclaim_finished()
    box, _ = _tls_state()
    if box[0] is None:
        box[0] = _SyncWorker()
    return box[0]


def _poison_worker(worker: _SyncWorker, done: threading.Event) -> None:
    box, in_flight = _tls_state()
    if box[0] is worker:
        box[0] = None
    in_flight.append((done, worker))


def _still_in_flight(budget: float) -> bool:
    """True when any abandoned collective of THIS caller thread is STILL
    running after waiting up to ``budget`` seconds for the stragglers to
    land."""
    deadline = time.monotonic() + max(budget, 0.0)
    _reclaim_finished()
    _, in_flight = _tls_state()
    pending = [done for done, _ in in_flight]
    stuck = False
    for done in pending:
        if not done.wait(max(deadline - time.monotonic(), 0.0)):
            stuck = True
            break
    _reclaim_finished()
    return stuck


def backoff_delay(
    attempt: int,
    *,
    base: float = 0.05,
    maximum: float = 2.0,
    jitter: float = 0.5,
    rng: Optional[random.Random] = None,
) -> float:
    """The ONE exponential-backoff law of the resilience stack:
    ``min(base * 2**(attempt-1), maximum) * (1 + jitter * u)`` with ``u``
    from ``rng`` (deterministic for a seeded ``random.Random``; 0 when
    ``rng`` is None or ``jitter`` is 0). Shared by
    :class:`ResilientGroup` retries and the federation's dark-region
    probe schedule (``federation.py`` quantizes it to exchange rounds)."""
    delay = min(base * (2 ** max(attempt - 1, 0)), maximum)
    if jitter and rng is not None:
        delay *= 1.0 + jitter * rng.random()
    return delay


def bounded_call(fn: Callable[[], Any], timeout: Optional[float]) -> Any:
    """Run ``fn()`` under the resilience deadline machinery: the
    per-caller-thread reusable daemon worker (:class:`_SyncWorker`), a
    bounded wait, and worker poisoning on a miss — so a wedged blocking
    call (a coordination-service RPC, a stuck collective probe) costs a
    bounded wait instead of hanging the caller. Raises
    :class:`SyncTimeoutError` on a miss; ``timeout=None`` runs inline.

    This is the standalone form of :meth:`ResilientGroup._bounded` for
    callers that are not a collective sequence (the federation's KV link
    polls) — it does NOT interact with the in-flight collective fence.
    """
    if timeout is None:
        return fn()
    worker = _get_worker()
    box, done = worker.submit(fn)
    if done.wait(timeout):
        return _harvest(box)
    _poison_worker(worker, done)
    raise SyncTimeoutError(f"bounded call missed its {timeout}s deadline")


def quorum_count(fraction: float, world: int) -> int:
    """Minimum surviving-rank count for a quorum ``fraction`` of ``world``
    — the single definition shared by the per-collective check
    (``ResilientGroup``) and the post-integrity-intersection check
    (``synclib._assemble``)."""
    return max(1, math.ceil(fraction * world))


class ResilientGroup(ProcessGroup):
    """Decorate any ``ProcessGroup`` with deadlines, retries, and graceful
    degradation. See the module docstring for the policy semantics.

    Args:
        inner: the group to wrap (``MultiHostGroup``, ``LocalReplicaGroup``,
            a test fake, or a ``FaultInjectionGroup`` chaos wrapper).
        timeout: per-collective deadline in seconds; ``None`` (default from
            ``config.sync_timeout()``) waits forever — the collective runs
            inline with no worker thread.
        retries: extra attempts after the first, for transient failures /
            timeouts (default from ``config.sync_retries()``).
        policy: ``"raise"`` | ``"local"`` | ``"quorum"`` (default from
            ``config.sync_degradation()``).
        quorum: minimum participating fraction of world size for the
            ``"quorum"`` policy (default from ``config.sync_quorum()``).
        backoff_base / backoff_max / backoff_jitter / seed: exponential
            backoff schedule ``min(base * 2**k, max) * (1 + jitter * u)``
            with ``u`` drawn from a ``random.Random(seed)`` — fully
            deterministic for a given seed and call sequence.
        reform_after: persistent-failure escalation threshold (default
            from ``config.sync_reform_after()``, 0 = disabled): after this
            many CONSECUTIVE quorum-degraded syncs missing the SAME ranks
            the group re-forms onto a survivors-only subgroup
            (``inner.new_subgroup``), so later syncs run full-speed
            undegraded. Only meaningful under ``policy="quorum"`` and a
            long-lived group object — the streak lives here, not in
            config state. See docs/fault-tolerance.md,
            "Survivor re-formation".
        health: share an existing :class:`SyncHealth` (used by
            :meth:`with_policy`); a fresh one is created by default.

    Examples::

        >>> from torcheval_tpu.distributed import default_process_group
        >>> from torcheval_tpu.resilience import ResilientGroup
        >>> group = ResilientGroup(
        ...     default_process_group(), timeout=30.0, policy="quorum"
        ... )
        >>> # value = sync_and_compute(metric, group)  # survives a dead host
        >>> group.health.timeouts
        0
    """

    def __init__(
        self,
        inner: ProcessGroup,
        *,
        timeout: Optional[float] = None,
        retries: Optional[int] = None,
        policy: Optional[str] = None,
        quorum: Optional[float] = None,
        backoff_base: float = 0.05,
        backoff_max: float = 2.0,
        backoff_jitter: float = 0.5,
        seed: int = 0,
        reform_after: Optional[int] = None,
        health: Optional[SyncHealth] = None,
    ) -> None:
        from torcheval_tpu import config

        self._inner = inner
        # the group collectives actually run on: ``inner`` until a
        # persistent-failure escalation re-forms onto a survivors-only
        # subgroup of it (see ``note_sync_result``)
        self._active: ProcessGroup = inner
        self.reform_after = (
            config.sync_reform_after()
            if reform_after is None
            else int(reform_after)
        )
        if self.reform_after < 0:
            raise ValueError(
                f"reform_after must be >= 0 (0 disables), got {reform_after}"
            )
        self.reform_count = 0
        self._missing_streak: Tuple[int, ...] = ()
        self._streak = 0
        self.timeout = (
            config.sync_timeout()
            if timeout is None
            else config._check_timeout(timeout)
        )
        self.retries = config.sync_retries() if retries is None else int(retries)
        policy = config.sync_degradation() if policy is None else policy
        self.policy = config.check_sync_policy(policy)
        if self.policy != "raise" and self.timeout is None:
            # a degrading policy without a deadline would still hang
            # forever on a plain group (degradation only fires on timeout
            # / transient / partial signals) — arm the default deadline so
            # the policy's bounded-failure promise actually holds
            self.timeout = DEFAULT_DEGRADING_TIMEOUT
        self.quorum = config.sync_quorum() if quorum is None else float(quorum)
        if not 0.0 < self.quorum <= 1.0:
            raise ValueError(
                f"quorum must be a fraction in (0, 1], got {self.quorum}"
            )
        self.backoff_base = backoff_base
        self.backoff_max = backoff_max
        self.backoff_jitter = backoff_jitter
        self.seed = seed
        self._rng = random.Random(seed)
        # (box, done) of a timed-out attempt still in flight on its worker
        self._late: Optional[Tuple[Dict[str, Any], threading.Event]] = None
        self._local_mode = isinstance(inner.unwrap(), LocalReplicaGroup)
        if health is None:
            health = SyncHealth()
            health.policy = self.policy  # shared health keeps its creator's
        self.health = health
        self.health.world_size = self.world_size

    # --------------------------------------------------------------- plumbing

    @property
    def world_size(self) -> int:
        return self._active.world_size

    @property
    def rank(self) -> int:
        return self._active.rank

    def unwrap(self) -> ProcessGroup:
        return self._active.unwrap()

    @property
    def is_member(self) -> bool:
        return self._active.is_member

    @property
    def ranks(self):
        return self._active.ranks

    def new_subgroup(self, ranks) -> "ResilientGroup":
        """Subgroup scoping composes with resilience: the active group's
        subgroup (ranks are relative to the group the caller sees — the
        reformed subgroup after an escalation), wrapped with THIS group's
        knobs and the same shared :class:`SyncHealth` (quorum fractions
        then apply to the SUBGROUP's world size —
        docs/fault-tolerance.md, "Subgroups")."""
        return ResilientGroup(
            self._active.new_subgroup(ranks),
            timeout=self.timeout,
            retries=self.retries,
            policy=self.policy,
            quorum=self.quorum,
            backoff_base=self.backoff_base,
            backoff_max=self.backoff_max,
            backoff_jitter=self.backoff_jitter,
            seed=self.seed,
            reform_after=self.reform_after,
            health=self.health,
        )

    @property
    def degradation_policy(self) -> str:
        """Read by ``synclib.sync_states`` to decide whether a corrupt or
        missing rank is droppable or fatal."""
        return self.policy

    @property
    def quorum_fraction(self) -> float:
        return self.quorum

    def with_policy(self, policy: str) -> "ResilientGroup":
        """A sibling wrapper around the same inner group and the same
        :class:`SyncHealth`, differing only in degradation policy (used by
        the toolkit's per-call ``on_failure=`` override). The sibling
        inherits this group's re-formation state (active subgroup,
        escalation streak), but its own future escalations do not write
        back — reuse the original group for a durable escalation record."""
        if policy == self.policy:
            return self
        sibling = ResilientGroup(
            self._inner,
            timeout=self.timeout,
            retries=self.retries,
            policy=policy,
            quorum=self.quorum,
            backoff_base=self.backoff_base,
            backoff_max=self.backoff_max,
            backoff_jitter=self.backoff_jitter,
            seed=self.seed,
            reform_after=self.reform_after,
            health=self.health,
        )
        sibling._active = self._active
        sibling._local_mode = self._local_mode
        sibling.reform_count = self.reform_count
        sibling._missing_streak = self._missing_streak
        sibling._streak = self._streak
        return sibling

    # ------------------------------------------------------------- observers

    def _note_event(
        self, reason: str, attempt: int = 0, detail: str = ""
    ) -> None:
        """Record one resilience lifecycle event (retry cause, degradation
        outcome, re-formation) when the observability recorder is on —
        the event-stream twin of the :class:`SyncHealth` counters. One
        attribute read when off; host-side only when on. Timeout/failure
        events carry the flight-ring tail (ISSUE 11) when the flight
        recorder is on: *which* collective in the sequence stalled."""
        if _OBS.enabled:
            from torcheval_tpu.obs.events import RetryEvent

            flight_tail = ""
            if _FLIGHT.enabled and reason in ("timeout", "failed"):
                flight_tail = _FLIGHT.tail_text()
            _OBS.record(
                RetryEvent(
                    rank=self.rank,
                    reason=reason,
                    attempt=attempt,
                    policy=self.policy,
                    detail=detail,
                    flight=flight_tail,
                )
            )

    def note_corrupt(self, rank: int) -> None:
        """Called by ``synclib`` when rank's payload fails its checksum."""
        with self.health._lock:
            self.health.corrupt_payloads += 1

    def note_sync_result(self, ranks: List[int], world: int) -> None:
        """Called by ``synclib`` with the final surviving-rank set of one
        whole state sync (after cross-collective intersection). Drives the
        persistent-failure escalation: ``reform_after`` consecutive
        degraded syncs missing the SAME ranks re-form this group onto the
        survivors (``_reform``) — effective from the NEXT sync."""
        alive = set(ranks)
        missing = tuple(r for r in range(world) if r not in alive)
        if not missing:
            self._missing_streak, self._streak = (), 0
        elif missing == self._missing_streak:
            self._streak += 1
        else:
            self._missing_streak, self._streak = missing, 1
        with self.health._lock:
            self.health.participating_ranks = tuple(ranks)
            self.health.consecutive_missing = self._missing_streak
            self.health.consecutive_missing_count = self._streak
            if len(ranks) == world:
                self.health.full_syncs += 1
                self.health.last_good_sync = time.monotonic()
            else:
                self.health.degraded_syncs += 1
        if (
            self.reform_after
            and self.policy == "quorum"
            and missing
            and self._streak >= self.reform_after
        ):
            self._reform(list(ranks))

    @property
    def was_reformed(self) -> bool:
        """True once this group escalated onto a survivors-only subgroup
        (stamped into every subsequent :class:`SyncProvenance`)."""
        return self.reform_count > 0

    def _reform(self, survivors: List[int]) -> None:
        """Escalate onto a survivors-only subgroup of the active group.

        ``survivors`` are ACTIVE-group-relative ranks. Subsequent
        collectives run on the subgroup — full-speed, undegraded — and
        provenance/quorum become subgroup-relative. The dead ranks'
        processes, if they ever come back, must rebuild their OWN group
        (e.g. via ``elastic.ElasticSession`` resume); consistent with the
        ``PartialGatherError`` contract, every surviving rank observed the
        same survivor set for ``reform_after`` consecutive syncs, so every
        survivor re-forms the same subgroup at the same sync index."""
        try:
            sub = self._active.new_subgroup(sorted(survivors))
        except NotImplementedError:
            # the inner group cannot scope to a subset (e.g. a bare test
            # fake): keep degrading per-sync rather than failing the sync
            self._missing_streak, self._streak = (), 0
            return
        self._active = sub
        self._local_mode = isinstance(sub.unwrap(), LocalReplicaGroup)
        self.reform_count += 1
        self._note_event("reform", detail=f"survivors {sorted(survivors)}")
        self._missing_streak, self._streak = (), 0
        with self.health._lock:
            self.health.reforms += 1
            self.health.reformed_to = tuple(sub.ranks)
            self.health.world_size = sub.world_size
            self.health.consecutive_missing = ()
            self.health.consecutive_missing_count = 0

    # -------------------------------------------------------------- deadline

    def _bounded(self, fn: Callable[[], Any]) -> Any:
        """Run one collective attempt under the deadline on the reusable
        daemon worker (see :class:`_SyncWorker`). On timeout the worker is
        abandoned — still blocked inside the collective — and the in-flight
        attempt is stashed on ``self._late`` so the retry loop can wait for
        its LATE completion instead of reissuing (reissuing while the first
        is still running would desynchronize the rank-wide collective
        order)."""
        if self.timeout is None:
            return fn()
        worker = _get_worker()
        box, done = worker.submit(fn)
        if done.wait(self.timeout):
            return _harvest(box)
        self._late = (box, done)
        _poison_worker(worker, done)  # its thread is stuck in `fn`
        raise SyncTimeoutError(
            f"metric sync collective missed its {self.timeout}s deadline"
        )

    def _next_backoff(self, attempt: int) -> float:
        """Deterministic exponential backoff with jitter for retry
        ``attempt`` (1-based) — the shared :func:`backoff_delay` law."""
        return backoff_delay(
            attempt,
            base=self.backoff_base,
            maximum=self.backoff_max,
            jitter=self.backoff_jitter,
            rng=self._rng,
        )

    # ------------------------------------------------------------ collectives

    def _resilient(
        self,
        fn: Callable[[], List[Any]],
        local_only: Callable[[], Tuple[List[Any], List[int]]],
        op: str = "collective",
        nbytes: int = 0,
    ) -> Tuple[List[Any], List[int]]:
        """Observability shell around :meth:`_resilient_impl`: with the
        recorder on, the whole collective (every retry attempt and the
        degradation decision) runs inside ONE span — the
        ``RetryEvent``\\ s emitted underneath parent to it, giving the
        per-collective, per-peer timing telemetry Prime-CCL-style
        operations need — and its wall time feeds the ``collective``
        latency digest. With the flight recorder on (ISSUE 11), the whole
        collective is ONE :class:`~torcheval_tpu.obs.flight.FlightRecord`
        — enqueued here, issued per attempt, completed/failed with the
        surviving ranks — visible MID-FLIGHT to the stall watchdog; a
        raised :class:`SyncTimeoutError` carries the ring tail as
        ``e.flight_tail``. Both off: one attribute read each, the
        original path."""
        record = None
        if _FLIGHT.enabled:
            record = _FLIGHT.start(
                op, payload_bytes=nbytes, rank=self.rank,
                world_size=self.world_size, state="enqueued",
            )
            if record is not None:
                inner = fn
                # the inner gather may run on the deadline WORKER thread,
                # whose own thread-local depth guard cannot see this
                # record — suppress explicitly so wrapped plain groups do
                # not record the same logical collective twice
                fn = lambda: _flight.suppressed(inner)  # noqa: E731
        try:
            if not _OBS.enabled:
                result = self._resilient_impl(fn, local_only, record)
            else:
                from torcheval_tpu.obs import hist as _obs_hist

                t0 = time.monotonic()
                try:
                    with _OBS.span("torcheval.collective"):
                        result = self._resilient_impl(fn, local_only, record)
                finally:
                    _obs_hist.observe("collective", time.monotonic() - t0)
        except BaseException as e:  # noqa: BLE001 — recorded, re-raised
            _FLIGHT.fail(record, f"{type(e).__name__}: {e}")
            if record is not None and isinstance(e, SyncTimeoutError):
                e.flight_tail = _FLIGHT.tail_text()
            raise
        values, ranks = result
        _FLIGHT.complete(
            record,
            ranks=tuple(ranks),
            detail=(
                "" if len(ranks) == self.world_size
                else f"degraded to ranks {list(ranks)}"
            ),
        )
        return result

    def _resilient_impl(
        self,
        fn: Callable[[], List[Any]],
        local_only: Callable[[], Tuple[List[Any], List[int]]],
        flight_record=None,
    ) -> Tuple[List[Any], List[int]]:
        """Run one collective with retries, then apply the degradation
        policy. Returns ``(payloads, participating_ranks)``, rank-aligned
        and ascending.

        A TIMED-OUT attempt is never reissued while still in flight: on a
        real multi-host group the original collective may eventually
        complete, and a second issue would pair off-by-one with the peers'
        collective sequence forever after. Retry attempts after a timeout
        instead extend the wait on the original (backoff + one more
        deadline); only transient wire errors — where the attempt
        definitively FAILED — reissue the collective.
        """
        h = self.health
        world = self.world_size
        partial: Optional[Dict[int, Any]] = None
        # FENCE: a previously abandoned collective — from ANY group in
        # this process, the fence is module-global — must complete (late)
        # before a new collective is issued, otherwise this rank's
        # collective sequence pairs off-by-one with its peers' forever
        # after. Stale results are drained and discarded; while one is
        # still running, this collective degrades WITHOUT issuing.
        self._late = None
        if _still_in_flight(self.timeout or 0.0):
            with h._lock:
                h.attempts += 1
                h.timeouts += 1
            self._note_event(
                "timeout", detail="abandoned collective still in flight"
            )
            return self._degrade(None, local_only)
        for attempt in range(self.retries + 1):
            delay = 0.0
            if attempt:
                with h._lock:
                    h.retries += 1
                delay = self._next_backoff(attempt)
            with h._lock:
                h.attempts += 1
            try:
                if self._late is not None:
                    # wait out the in-flight original instead of reissuing
                    box, done = self._late
                    if not done.wait(delay + (self.timeout or 0.0)):
                        with h._lock:
                            h.timeouts += 1
                        self._note_event(
                            "timeout", attempt, "late original still running"
                        )
                        continue
                    self._late = None
                    result = _harvest(box)
                else:
                    if delay:
                        time.sleep(delay)
                    _FLIGHT.issued(flight_record)
                    result = self._bounded(fn)
            except PartialGatherError as e:
                with h._lock:
                    h.partial_gathers += 1
                self._note_event(
                    "partial-gather", attempt, f"ranks {sorted(e.values)}"
                )
                partial = dict(e.values)
                # peer loss is not transient: a quorum of survivors is
                # usable immediately, without burning the retry budget
                if self.policy == "quorum" and len(
                    self._with_own(partial, local_only)
                ) >= self._quorum_count():
                    break
                continue
            except TransientSyncError:
                with h._lock:
                    h.transient_errors += 1
                self._note_event("transient", attempt)
                continue
            except SyncTimeoutError:
                with h._lock:
                    h.timeouts += 1
                self._note_event("timeout", attempt)
                continue
            return list(result), list(range(world))
        return self._degrade(partial, local_only)

    def _quorum_count(self) -> int:
        return quorum_count(self.quorum, self.world_size)

    def _with_own(
        self,
        partial: Optional[Dict[int, Any]],
        local_only: Callable[[], Tuple[List[Any], List[int]]],
    ) -> Dict[int, Any]:
        """Survivor map: whatever arrived, plus this rank's own payload
        (always available without any wire traffic)."""
        survivors = dict(partial or {})
        own_vals, own_ranks = local_only()
        for r, v in zip(own_ranks, own_vals):
            survivors.setdefault(r, v)
        return survivors

    def _degrade(
        self,
        partial: Optional[Dict[int, Any]],
        local_only: Callable[[], Tuple[List[Any], List[int]]],
    ) -> Tuple[List[Any], List[int]]:
        h = self.health
        if self.policy == "local":
            vals, ranks = local_only()
            self._note_event("degraded-local", detail=f"ranks {list(ranks)}")
            return list(vals), list(ranks)
        if self.policy == "quorum":
            survivors = self._with_own(partial, local_only)
            ranks = sorted(survivors)
            if len(ranks) >= self._quorum_count():
                self._note_event(
                    "degraded-quorum", detail=f"ranks {ranks}"
                )
                return [survivors[r] for r in ranks], ranks
            self._note_event(
                "failed",
                detail=f"quorum not met: {len(ranks)}/{self.world_size}",
            )
            raise SyncTimeoutError(
                f"metric sync quorum not met: {len(ranks)}/{self.world_size} "
                f"ranks responded, quorum requires >= {self._quorum_count()} "
                f"(fraction {self.quorum})"
            )
        self._note_event("failed", detail="policy 'raise'")
        raise SyncTimeoutError(
            f"metric sync failed after {self.retries + 1} attempt(s) "
            f"({h.timeouts} timeouts, {h.transient_errors} transient errors "
            f"so far on this group); policy 'raise' forbids degradation"
        )

    def _local_object(self, obj: Any) -> Tuple[List[Any], List[int]]:
        if self._local_mode:
            # under LocalReplicaGroup the argument IS the per-replica list;
            # "this rank's own payload" is the controller's replica 0
            return [obj[self.rank]], [self.rank]
        return [obj], [self.rank]

    def _local_array(self, x: Any) -> Tuple[List[Any], List[int]]:
        if self._local_mode:
            return [np.asarray(x[self.rank])], [self.rank]
        return [np.asarray(x)], [self.rank]

    def allgather_object_with_ranks(
        self, obj: Any
    ) -> Tuple[List[Any], List[int]]:
        return self._resilient(
            lambda: self._active.allgather_object(obj),
            lambda: self._local_object(obj),
            "allgather_object",
            _flight.payload_nbytes(obj),
        )

    def allgather_array_with_ranks(self, x: Any) -> Tuple[List[Any], List[int]]:
        return self._resilient(
            lambda: self._active.allgather_array(x),
            lambda: self._local_array(x),
            "allgather_array",
            _flight.payload_nbytes(x),
        )

    def _full_or_raise(
        self, gathered: Tuple[List[Any], List[int]]
    ) -> List[Any]:
        """The base-class ``allgather_*`` contract is one payload per rank
        IN RANK ORDER; a degraded (partial) result cannot satisfy it, and
        silently returning fewer entries would mis-attribute ranks in any
        positional caller. Rank-aware callers use the ``_with_ranks``
        variants (as ``synclib`` does)."""
        values, ranks = gathered
        if len(ranks) == self.world_size:
            return values
        raise SyncTimeoutError(
            f"gather degraded to ranks {ranks} of {self.world_size}; the "
            "plain allgather contract (one payload per rank, in rank "
            "order) cannot represent a partial result — use "
            "allgather_object_with_ranks/allgather_array_with_ranks"
        )

    def allgather_object(self, obj: Any) -> List[Any]:
        return self._full_or_raise(self.allgather_object_with_ranks(obj))

    def allgather_array(self, x: Any) -> List[Any]:
        return self._full_or_raise(self.allgather_array_with_ranks(x))
