"""Window metric tests vs the reference oracle. Windowed metrics have
bespoke ring-buffer/merge semantics, so each test drives ours and the
reference through identical update/merge sequences and compares outputs."""

import jax.numpy as jnp
import numpy as np
import pytest
import torch

from tests.ref_oracle import load_reference_metrics
from torcheval_tpu.metrics import (
    WindowedBinaryAUROC,
    WindowedBinaryNormalizedEntropy,
    WindowedClickThroughRate,
    WindowedMeanSquaredError,
    WindowedWeightedCalibration,
)
from torcheval_tpu.utils.test_utils.metric_class_tester import (
    MetricClassTester,
    assert_result_close,
)

REF_M, _ = load_reference_metrics()
RNG = np.random.default_rng(23)


def _drive(ours, ref, update_args_seq):
    """Apply the same update sequence to both metrics, compare compute()."""
    for args in update_args_seq:
        ours.update(*[jnp.asarray(a) for a in args])
        ref.update(*[torch.tensor(a) for a in args])
    ours_result = ours.compute()
    ref_result = ref.compute()
    if isinstance(ref_result, tuple):
        for o, r in zip(ours_result, ref_result):
            assert_result_close(o, np.asarray(r), atol=1e-4, rtol=1e-4)
    else:
        assert_result_close(ours_result, np.asarray(ref_result), atol=1e-4, rtol=1e-4)


class TestWindowedClickThroughRate(MetricClassTester):
    @pytest.mark.parametrize("enable_lifetime", [True, False])
    @pytest.mark.parametrize("n_updates", [2, 3, 7])
    def test_windowed_ctr(self, enable_lifetime, n_updates):
        updates = [
            (RNG.integers(0, 2, size=(8,)).astype(np.float32),)
            for _ in range(n_updates)
        ]
        _drive(
            WindowedClickThroughRate(
                max_num_updates=3, enable_lifetime=enable_lifetime
            ),
            REF_M.WindowedClickThroughRate(
                max_num_updates=3, enable_lifetime=enable_lifetime
            ),
            updates,
        )

    def test_windowed_ctr_harness(self):
        inputs = [RNG.integers(0, 2, size=(8,)).astype(np.float32) for _ in range(8)]
        ref = REF_M.WindowedClickThroughRate(max_num_updates=4)
        for x in inputs:
            ref.update(torch.tensor(x))
        expected = tuple(np.asarray(r) for r in ref.compute())
        # merge path: reference merge concatenates each replica's window
        # (2 updates per rank < max 4, so every rank's columns survive)
        ref_ranks = [REF_M.WindowedClickThroughRate(max_num_updates=4) for _ in range(4)]
        for i, x in enumerate(inputs):
            ref_ranks[i // 2].update(torch.tensor(x))
        ref_ranks[0].merge_state(ref_ranks[1:])
        merge_expected = tuple(np.asarray(r) for r in ref_ranks[0].compute())
        self.run_class_implementation_tests(
            metric=WindowedClickThroughRate(max_num_updates=4),
            state_names={
                "max_num_updates",
                "total_updates",
                "click_total",
                "weight_total",
                "windowed_click_total",
                "windowed_weight_total",
            },
            update_kwargs={"input": inputs},
            compute_result=expected,
            merge_and_compute_result=merge_expected,
        )

    def test_windowed_ctr_multitask(self):
        updates = [
            (RNG.integers(0, 2, size=(2, 6)).astype(np.float32),) for _ in range(5)
        ]
        _drive(
            WindowedClickThroughRate(num_tasks=2, max_num_updates=2),
            REF_M.WindowedClickThroughRate(num_tasks=2, max_num_updates=2),
            updates,
        )


class TestWindowedNormalizedEntropy(MetricClassTester):
    @pytest.mark.parametrize("enable_lifetime", [True, False])
    def test_windowed_ne(self, enable_lifetime):
        updates = [
            (
                RNG.uniform(0.1, 0.9, size=(6,)).astype(np.float32),
                RNG.integers(0, 2, size=(6,)).astype(np.float32),
            )
            for _ in range(5)
        ]
        _drive(
            WindowedBinaryNormalizedEntropy(
                max_num_updates=2, enable_lifetime=enable_lifetime
            ),
            REF_M.WindowedBinaryNormalizedEntropy(
                max_num_updates=2, enable_lifetime=enable_lifetime
            ),
            updates,
        )

    def test_windowed_ne_multitask_merge(self):
        def make(ref=False):
            cls = (
                REF_M.WindowedBinaryNormalizedEntropy
                if ref
                else WindowedBinaryNormalizedEntropy
            )
            return cls(num_tasks=2, max_num_updates=3)

        updates = [
            (
                RNG.uniform(0.1, 0.9, size=(2, 4)).astype(np.float32),
                RNG.integers(0, 2, size=(2, 4)).astype(np.float32),
            )
            for _ in range(4)
        ]
        ours_a, ours_b = make(), make()
        ref_a, ref_b = make(ref=True), make(ref=True)
        for x, t in updates[:2]:
            ours_a.update(jnp.asarray(x), jnp.asarray(t))
            ref_a.update(torch.tensor(x), torch.tensor(t))
        for x, t in updates[2:]:
            ours_b.update(jnp.asarray(x), jnp.asarray(t))
            ref_b.update(torch.tensor(x), torch.tensor(t))
        ours_a.merge_state([ours_b])
        ref_a.merge_state([ref_b])
        for o, r in zip(ours_a.compute(), ref_a.compute()):
            assert_result_close(o, np.asarray(r), atol=1e-4, rtol=1e-4)
        # merged metric remains updatable, cursor wraps identically
        x, t = updates[0]
        ours_a.update(jnp.asarray(x), jnp.asarray(t))
        ref_a.update(torch.tensor(x), torch.tensor(t))
        for o, r in zip(ours_a.compute(), ref_a.compute()):
            assert_result_close(o, np.asarray(r), atol=1e-4, rtol=1e-4)


class TestWindowedMeanSquaredError(MetricClassTester):
    @pytest.mark.parametrize("enable_lifetime", [True, False])
    @pytest.mark.parametrize("n_updates", [1, 4])
    def test_windowed_mse(self, enable_lifetime, n_updates):
        updates = [
            (
                RNG.uniform(size=(6,)).astype(np.float32),
                RNG.uniform(size=(6,)).astype(np.float32),
            )
            for _ in range(n_updates)
        ]
        _drive(
            WindowedMeanSquaredError(
                max_num_updates=2, enable_lifetime=enable_lifetime
            ),
            REF_M.WindowedMeanSquaredError(
                max_num_updates=2, enable_lifetime=enable_lifetime
            ),
            updates,
        )

    def test_windowed_mse_multitask(self):
        updates = [
            (
                RNG.uniform(size=(5, 3)).astype(np.float32),
                RNG.uniform(size=(5, 3)).astype(np.float32),
            )
            for _ in range(4)
        ]
        _drive(
            WindowedMeanSquaredError(num_tasks=3, max_num_updates=2),
            REF_M.WindowedMeanSquaredError(num_tasks=3, max_num_updates=2),
            updates,
        )

    def test_windowed_mse_num_tasks_shape_check(self):
        m = WindowedMeanSquaredError(num_tasks=2)
        with pytest.raises(ValueError, match="num_tasks = 2"):
            m.update(jnp.ones(4), jnp.ones(4))
        with pytest.raises(ValueError, match="num_tasks = 1"):
            WindowedMeanSquaredError().update(jnp.ones((4, 2)), jnp.ones((4, 2)))


class TestWindowedWeightedCalibration(MetricClassTester):
    @pytest.mark.parametrize("enable_lifetime", [True, False])
    def test_windowed_wc(self, enable_lifetime):
        updates = [
            (
                RNG.uniform(size=(6,)).astype(np.float32),
                RNG.integers(0, 2, size=(6,)).astype(np.float32),
            )
            for _ in range(5)
        ]
        _drive(
            WindowedWeightedCalibration(
                max_num_updates=2, enable_lifetime=enable_lifetime
            ),
            REF_M.WindowedWeightedCalibration(
                max_num_updates=2, enable_lifetime=enable_lifetime
            ),
            updates,
        )


class TestWindowedBinaryAUROC(MetricClassTester):
    @pytest.mark.parametrize("batch", [3, 5, 11])
    def test_windowed_auroc_insert_cases(self, batch):
        # batches chosen to hit: fits-in-rest, wraps, oversized (>= max 10)
        updates = [
            (
                RNG.uniform(size=(batch,)).astype(np.float32),
                RNG.integers(0, 2, size=(batch,)).astype(np.float32),
            )
            for _ in range(4)
        ]
        _drive(
            WindowedBinaryAUROC(max_num_samples=10),
            REF_M.WindowedBinaryAUROC(max_num_samples=10),
            updates,
        )

    def test_windowed_auroc_multitask(self):
        updates = [
            (
                RNG.uniform(size=(2, 4)).astype(np.float32),
                RNG.integers(0, 2, size=(2, 4)).astype(np.float32),
            )
            for _ in range(3)
        ]
        _drive(
            WindowedBinaryAUROC(num_tasks=2, max_num_samples=6),
            REF_M.WindowedBinaryAUROC(num_tasks=2, max_num_samples=6),
            updates,
        )

    def test_windowed_auroc_merge(self):
        def pair():
            return (
                RNG.uniform(size=(4,)).astype(np.float32),
                RNG.integers(0, 2, size=(4,)).astype(np.float32),
            )

        ours = [WindowedBinaryAUROC(max_num_samples=6) for _ in range(3)]
        refs = [REF_M.WindowedBinaryAUROC(max_num_samples=6) for _ in range(3)]
        for o, r in zip(ours, refs):
            x, t = pair()
            o.update(jnp.asarray(x), jnp.asarray(t))
            r.update(torch.tensor(x), torch.tensor(t))
        ours[0].merge_state(ours[1:])
        refs[0].merge_state(refs[1:])
        assert_result_close(
            ours[0].compute(), np.asarray(refs[0].compute()), atol=1e-4, rtol=1e-4
        )

    def test_windowed_auroc_param_validation(self):
        with pytest.raises(ValueError, match="num_tasks"):
            WindowedBinaryAUROC(num_tasks=0)
        with pytest.raises(ValueError, match="max_num_samples"):
            WindowedBinaryAUROC(max_num_samples=0)
