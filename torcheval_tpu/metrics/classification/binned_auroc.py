"""Binned AUROC class metrics.

Parity: reference torcheval/metrics/classification/binned_auroc.py
(BinaryBinnedAUROC :31 with buffered inputs/targets, MulticlassBinnedAUROC
:153). Returns ``(auroc, threshold)`` from compute.
"""

from __future__ import annotations

from typing import List, Optional, Tuple, Union

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.classification.auprc import _BufferedPairMetric
from torcheval_tpu.metrics.functional.classification.auroc import (
    _binary_auroc_update_input_check,
    _multiclass_auroc_update_input_check,
)
from torcheval_tpu.metrics.functional.classification.binned_auroc import (
    DEFAULT_NUM_THRESHOLD,
    _binary_binned_auroc_compute_jit,
    _binary_binned_auroc_param_check,
    _hist_binned_auroc_compute,
    _hist_binned_flat_index,
    _hist_binned_update,
    _multiclass_binned_auroc_compute_jit,
    _multiclass_binned_auroc_param_check,
)
from torcheval_tpu.metrics.functional.tensor_utils import create_threshold_tensor
from torcheval_tpu.metrics import shardspec
from torcheval_tpu.metrics.metric import MergeKind, Metric, UpdatePlan
from torcheval_tpu.metrics.shardspec import ShardSpec


class BinaryBinnedAUROC(_BufferedPairMetric):
    """Binned AUROC for binary classification.

    Examples::

        >>> import jax.numpy as jnp
        >>> from torcheval_tpu.metrics import BinaryBinnedAUROC
        >>> metric = BinaryBinnedAUROC(threshold=5)
        >>> metric.update(jnp.array([0.1, 0.5, 0.7, 0.8]),
        ...               jnp.array([0, 0, 1, 1]))
        >>> auroc, thresholds = metric.compute()
    """

    _concat_axis = -1

    _extra_device_attrs = ("threshold",)

    def __init__(
        self,
        *,
        num_tasks: int = 1,
        threshold: Union[int, List[float], jax.Array] = DEFAULT_NUM_THRESHOLD,
        device=None,
    ) -> None:
        super().__init__(device=device)
        threshold = jax.device_put(create_threshold_tensor(threshold), self.device)
        _binary_binned_auroc_param_check(num_tasks, threshold)
        self.num_tasks = num_tasks
        self.threshold = threshold

    def update(self, input, target) -> "BinaryBinnedAUROC":
        input, target = self._input(input), self._input(target)
        _binary_auroc_update_input_check(input, target, self.num_tasks)
        self._append(input, target)
        return self

    def compute(self) -> Tuple[jax.Array, jax.Array]:
        # pad-neutral: padded scores are -inf, below every finite threshold
        inputs, targets = self._padded()
        return (
            _binary_binned_auroc_compute_jit(inputs, targets, self.threshold),
            self.threshold,
        )


class HistogramBinnedAUROC(Metric[Tuple[jax.Array, jax.Array]]):
    """Binned AUROC from a per-bin count histogram — O(num_thresholds)
    state, O(batch·log T) updates, and the library's million-bin,
    SHARDABLE binned-AUROC family.

    Unlike :class:`BinaryBinnedAUROC` (which buffers raw examples), the
    state is one ``(2T,)`` int32 histogram: each sample increments the
    cell of the inter-threshold bin its score falls in (negatives in
    ``[0, T)``, positives in ``[T, 2T)``); ``compute()`` rebuilds the
    per-threshold tp/fp counters by suffix sums — integer-exact, so the
    result is bit-identical however the histogram was accumulated,
    merged, or sharded. That makes threshold grids of 1M+ bins
    practical: per-rank state drops to ``2T/world`` cells under a
    ``shard`` context, updates scatter owned bins natively
    (``ops.segment``) and outbox the rest, and sync ships
    ``shard + outbox`` instead of the whole grid.

    Examples::

        >>> import jax.numpy as jnp
        >>> from torcheval_tpu.metrics import HistogramBinnedAUROC
        >>> metric = HistogramBinnedAUROC(threshold=4)
        >>> metric.update(jnp.array([0.1, 0.5, 0.7, 0.8]),
        ...               jnp.array([0, 0, 1, 1]))
        >>> auroc, thresholds = metric.compute()
    """

    _extra_device_attrs = ("threshold",)

    def __init__(
        self,
        *,
        threshold: Union[int, List[float], jax.Array] = DEFAULT_NUM_THRESHOLD,
        device=None,
        shard=None,
    ) -> None:
        super().__init__(device=device, shard=shard)
        threshold = jax.device_put(
            create_threshold_tensor(threshold), self._input_placement()
        )
        _binary_binned_auroc_param_check(1, threshold)
        self.threshold = threshold
        self.num_thresholds = int(threshold.shape[0])
        self._add_state(
            "hist",
            jnp.zeros((2 * self.num_thresholds,), dtype=jnp.int32),
            merge=MergeKind.SUM,
            shard=ShardSpec(axis=0),
        )
        shardspec.enable_routing(self, "hist")

    def _update_plan(self, input, target):
        input, target = self._input(input), self._input(target)
        _binary_auroc_update_input_check(input, target, 1)
        if self._route_active("hist"):
            names = self._routed_states["hist"]
            n = int(target.shape[0])
            shardspec.ensure_outbox_capacity(self, "hist", n)
            info = self._sharded_states["hist"]
            start, stop = self._shard_ctx.shard_range(info.logical_shape[0])
            kernel = shardspec.route_scatter_kernel(
                _hist_binned_flat_index, start, stop
            )

            def finalize():
                setattr(self, names.obh, getattr(self, names.obh) + n)

            # the masked routed twin keeps sharded instances
            # retrace-proof under shape bucketing (threshold carries no
            # ragged axis — only the sample vectors pad)
            return UpdatePlan(
                kernel,
                ("hist", names.obi, names.obn),
                (input, target, self.threshold),
                (),
                transform=True,
                finalize=finalize,
                masked_kernel=shardspec.route_scatter_kernel_masked(
                    _hist_binned_flat_index, start, stop
                ),
                batch_axes=(("batch",), ("batch",), None),
            )
        return UpdatePlan(
            _hist_binned_update,
            ("hist",),
            (input, target, self.threshold),
        )

    def update(self, input, target) -> "HistogramBinnedAUROC":
        return self._apply_update_plan(self._update_plan(input, target))

    def compute(self) -> Tuple[jax.Array, jax.Array]:
        return (
            _hist_binned_auroc_compute(
                self._logical_state("hist"), self.num_thresholds
            ),
            self.threshold,
        )


class MulticlassBinnedAUROC(_BufferedPairMetric):
    """Binned one-vs-rest AUROC for multiclass classification.

    See the functional docstring for the documented divergence from the
    reference's (buggy) class-axis reduction.

    Examples::

        >>> import jax.numpy as jnp
        >>> from torcheval_tpu.metrics import MulticlassBinnedAUROC
        >>> metric = MulticlassBinnedAUROC(num_classes=3, threshold=5)
        >>> metric.update(jnp.array([[0.8, 0.1, 0.1], [0.2, 0.7, 0.1],
        ...                  [0.1, 0.2, 0.7], [0.3, 0.5, 0.2]]), jnp.array([0, 1, 2, 1]))
        >>> metric.compute()
        (Array(1., dtype=float32), Array([0.  , 0.25, 0.5 , 0.75, 1.  ], dtype=float32))
    """

    _extra_device_attrs = ("threshold",)

    def __init__(
        self,
        *,
        num_classes: int,
        threshold: Union[int, List[float], jax.Array] = DEFAULT_NUM_THRESHOLD,
        average: Optional[str] = "macro",
        device=None,
    ) -> None:
        super().__init__(device=device)
        threshold = jax.device_put(create_threshold_tensor(threshold), self.device)
        _multiclass_binned_auroc_param_check(num_classes, threshold, average)
        self.num_classes = num_classes
        self.threshold = threshold
        self.average = average

    def update(self, input, target) -> "MulticlassBinnedAUROC":
        input, target = self._input(input), self._input(target)
        _multiclass_auroc_update_input_check(input, target, self.num_classes)
        self._append(input, target)
        return self

    def compute(self) -> Tuple[jax.Array, jax.Array]:
        inputs, targets = self._padded()
        auroc = _multiclass_binned_auroc_compute_jit(
            inputs, targets, self.threshold
        )
        if self.average == "macro":
            return jnp.mean(auroc), self.threshold
        return auroc, self.threshold
