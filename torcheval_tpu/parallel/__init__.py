from torcheval_tpu.parallel.moe import moe_apply, moe_reference
from torcheval_tpu.parallel.pipeline import (
    pipeline_apply,
    pipeline_reference,
)
from torcheval_tpu.parallel.ring_attention import (
    dense_reference_attention,
    ring_attention,
)

__all__ = [
    "dense_reference_attention",
    "moe_apply",
    "moe_reference",
    "pipeline_apply",
    "pipeline_reference",
    "ring_attention",
]
