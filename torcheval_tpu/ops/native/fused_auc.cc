// Fused AUC histogram — C++ XLA custom-call (CPU host kernel).
//
// The native component of the fused approximate-AUC op: the TPU path is the
// Pallas kernel in torcheval_tpu/ops/fused_auc.py; this is the host-side
// equivalent, registered with XLA through the FFI API so it participates in
// jit programs on the CPU backend. Parity target: the role of fbgemm_gpu's
// fused CUDA AUC kernel in the reference
// (torcheval/metrics/functional/classification/auroc.py:161-173).
//
// Inputs:  scores (T, N) f32 in [0, 1] (clamped), labels (T, N) f32 {0, 1},
//          weights (T, N) f32.
// Outputs: hist (T, 2, B) f32 — per task, row 0 = positive-weight histogram,
//          row 1 = negative-weight histogram over B equal score bins.
//
// Build: g++ -O3 -march=native -shared -fPIC (see native/build.py).

#include <algorithm>
#include <cstdint>

#include "xla/ffi/api/ffi.h"

namespace ffi = xla::ffi;

static ffi::Error FusedAucHistogramImpl(ffi::Buffer<ffi::F32> scores,
                                        ffi::Buffer<ffi::F32> labels,
                                        ffi::Buffer<ffi::F32> weights,
                                        ffi::ResultBuffer<ffi::F32> hist) {
  const auto dims = scores.dimensions();
  if (dims.size() != 2) {
    return ffi::Error::InvalidArgument("scores must be rank 2 (tasks, n)");
  }
  const int64_t num_tasks = dims[0];
  const int64_t n = dims[1];
  const auto ldims = labels.dimensions();
  const auto wdims = weights.dimensions();
  if (ldims.size() != 2 || ldims[0] != num_tasks || ldims[1] != n ||
      wdims.size() != 2 || wdims[0] != num_tasks || wdims[1] != n) {
    return ffi::Error::InvalidArgument(
        "labels/weights must match scores shape (tasks, n)");
  }
  const auto hist_dims = hist->dimensions();
  if (hist_dims.size() != 3 || hist_dims[0] != num_tasks ||
      hist_dims[1] != 2) {
    return ffi::Error::InvalidArgument("hist must be (tasks, 2, bins)");
  }
  const int64_t bins = hist_dims[2];

  const float* s = scores.typed_data();
  const float* l = labels.typed_data();
  const float* w = weights.typed_data();
  float* h = hist->typed_data();
  std::fill(h, h + num_tasks * 2 * bins, 0.0f);

  for (int64_t t = 0; t < num_tasks; ++t) {
    float* pos = h + t * 2 * bins;
    float* neg = pos + bins;
    const int64_t base = t * n;
    for (int64_t i = 0; i < n; ++i) {
      float sc = s[base + i];
      sc = sc < 0.0f ? 0.0f : (sc > 1.0f ? 1.0f : sc);
      int64_t b = static_cast<int64_t>(sc * static_cast<float>(bins));
      if (b >= bins) b = bins - 1;
      const float wi = w[base + i];
      const float li = l[base + i];
      pos[b] += wi * li;
      neg[b] += wi * (1.0f - li);
    }
  }
  return ffi::Error::Success();
}

XLA_FFI_DEFINE_HANDLER_SYMBOL(FusedAucHistogram, FusedAucHistogramImpl,
                              ffi::Ffi::Bind()
                                  .Arg<ffi::Buffer<ffi::F32>>()
                                  .Arg<ffi::Buffer<ffi::F32>>()
                                  .Arg<ffi::Buffer<ffi::F32>>()
                                  .Ret<ffi::Buffer<ffi::F32>>());
