"""Confusion matrix class metrics.

Parity: reference torcheval/metrics/classification/confusion_matrix.py
(Multiclass :26, Binary :216) — a single (C, C) counter state with SUM merge.
"""

from __future__ import annotations

from typing import Optional, TypeVar

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.functional.classification.confusion_matrix import (
    _binary_confusion_matrix_update_input_check,
    _binary_confusion_matrix_update_jit,
    _binary_confusion_matrix_update_masked,
    _confusion_matrix_compute,
    _confusion_matrix_param_check,
    _confusion_matrix_update_input_check,
    _confusion_matrix_update_jit,
    _confusion_matrix_update_masked,
)
from torcheval_tpu.metrics.metric import MergeKind, Metric, UpdatePlan

TMulticlassConfusionMatrix = TypeVar(
    "TMulticlassConfusionMatrix", bound="MulticlassConfusionMatrix"
)


class MulticlassConfusionMatrix(Metric[jax.Array]):
    """Multiclass confusion matrix; entry (i, j) counts true class i
    predicted as class j.

    Examples::

        >>> import jax.numpy as jnp
        >>> from torcheval_tpu.metrics import MulticlassConfusionMatrix
        >>> metric = MulticlassConfusionMatrix(4)
        >>> metric.update(jnp.array([0, 2, 1, 3]), jnp.array([0, 1, 2, 3]))
    """

    def __init__(
        self,
        num_classes: int,
        *,
        normalize: Optional[str] = None,
        device=None,
    ) -> None:
        super().__init__(device=device)
        _confusion_matrix_param_check(num_classes, normalize)
        self.num_classes = num_classes
        self.normalize = normalize
        self._add_state(
            "confusion_matrix",
            jnp.zeros((num_classes, num_classes), dtype=jnp.int32),
            merge=MergeKind.SUM,
        )

    # plans carry mask-aware kernel twins (metrics/_bucket.py)
    _bucketed_update = True

    def _update_plan(self, input, target):
        input, target = self._input(input), self._input(target)
        _confusion_matrix_update_input_check(input, target, self.num_classes)
        return UpdatePlan(
            _confusion_matrix_update_jit,
            ("confusion_matrix",),
            (input, target),
            (self.num_classes,),
            masked_kernel=_confusion_matrix_update_masked,
            batch_axes=(("batch",), ("batch",)),
        )

    def update(
        self: TMulticlassConfusionMatrix, input, target
    ) -> TMulticlassConfusionMatrix:
        # one fused dispatch: scatter kernel + matrix add
        return self._apply_update_plan(self._update_plan(input, target))

    def compute(self) -> jax.Array:
        return _confusion_matrix_compute(self.confusion_matrix, self.normalize)

    def normalized(self, normalize: Optional[str] = None) -> jax.Array:
        """Return the matrix under a different normalization
        (reference confusion_matrix.py:198-206)."""
        _confusion_matrix_param_check(self.num_classes, normalize)
        return _confusion_matrix_compute(self.confusion_matrix, normalize)


class BinaryConfusionMatrix(MulticlassConfusionMatrix):
    """2x2 confusion matrix for binary classification with thresholded
    score inputs.

    Examples::

        >>> import jax.numpy as jnp
        >>> from torcheval_tpu.metrics import BinaryConfusionMatrix
        >>> metric = BinaryConfusionMatrix()
        >>> metric.update(jnp.array([0.2, 0.8, 0.6, 0.3]), jnp.array([0, 1, 1, 0]))
        >>> metric.compute()
        Array([[2, 0],
               [0, 2]], dtype=int32)
    """

    def __init__(
        self,
        *,
        threshold: float = 0.5,
        normalize: Optional[str] = None,
        device=None,
    ) -> None:
        super().__init__(num_classes=2, normalize=normalize, device=device)
        self.threshold = threshold

    def _update_plan(self, input, target):
        input, target = self._input(input), self._input(target)
        _binary_confusion_matrix_update_input_check(input, target)
        return UpdatePlan(
            _binary_confusion_matrix_update_jit,
            ("confusion_matrix",),
            (input, target),
            (float(self.threshold),),
            masked_kernel=_binary_confusion_matrix_update_masked,
            batch_axes=(("batch",), ("batch",)),
        )

    def update(self, input, target) -> "BinaryConfusionMatrix":
        return self._apply_update_plan(self._update_plan(input, target))
