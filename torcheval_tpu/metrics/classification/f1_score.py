"""F1 score class metrics.

Parity: reference torcheval/metrics/classification/f1_score.py
(Multiclass :26, Binary :161) — O(1) counter states with SUM merge.
"""

from __future__ import annotations

from typing import Optional, TypeVar

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.functional.classification.f1_score import (
    _binary_f1_score_update_input_check,
    _binary_f1_score_update_jit,
    _binary_f1_score_update_masked,
    _f1_score_compute,
    _f1_score_param_check,
    _f1_score_update_input_check,
    _f1_score_update_jit,
    _f1_score_update_masked,
)
from torcheval_tpu.metrics.metric import MergeKind, Metric, UpdatePlan

TF1Score = TypeVar("TF1Score", bound="MulticlassF1Score")


class MulticlassF1Score(Metric[jax.Array]):
    """F1 score for multiclass classification.

    Examples::

        >>> import jax.numpy as jnp
        >>> from torcheval_tpu.metrics import MulticlassF1Score
        >>> metric = MulticlassF1Score()
        >>> metric.update(jnp.array([0, 2, 1, 3]), jnp.array([0, 1, 2, 3]))
        >>> metric.compute()
        Array(0.5, dtype=float32)
    """

    def __init__(
        self,
        *,
        num_classes: Optional[int] = None,
        average: Optional[str] = "micro",
        device=None,
    ) -> None:
        super().__init__(device=device)
        _f1_score_param_check(num_classes, average)
        self.num_classes = num_classes
        self.average = average
        shape = () if average == "micro" else (num_classes,)
        self._add_state("num_tp", jnp.zeros(shape), merge=MergeKind.SUM)
        self._add_state("num_label", jnp.zeros(shape), merge=MergeKind.SUM)
        self._add_state("num_prediction", jnp.zeros(shape), merge=MergeKind.SUM)

    # plans carry mask-aware kernel twins (metrics/_bucket.py)
    _bucketed_update = True

    def _update_plan(self: TF1Score, input, target):
        input, target = self._input(input), self._input(target)
        _f1_score_update_input_check(input, target, self.num_classes)
        # one fused dispatch: kernel + the three counter adds
        return UpdatePlan(
            _f1_score_update_jit,
            ("num_tp", "num_label", "num_prediction"),
            (input, target),
            (self.num_classes, self.average),
            masked_kernel=_f1_score_update_masked,
            batch_axes=(("batch",), ("batch",)),
        )

    def update(self: TF1Score, input, target) -> TF1Score:
        return self._apply_update_plan(self._update_plan(input, target))

    def compute(self) -> jax.Array:
        return _f1_score_compute(
            self.num_tp, self.num_label, self.num_prediction, self.average
        )


class BinaryF1Score(MulticlassF1Score):
    """Binary F1 score with thresholded score inputs.

    Examples::

        >>> import jax.numpy as jnp
        >>> from torcheval_tpu.metrics import BinaryF1Score
        >>> metric = BinaryF1Score()
        >>> metric.update(jnp.array([0.2, 0.8, 0.6, 0.3]), jnp.array([0, 1, 1, 0]))
        >>> metric.compute()
        Array(1., dtype=float32)
    """

    def __init__(self, *, threshold: float = 0.5, device=None) -> None:
        super().__init__(device=device)
        self.threshold = threshold

    def _update_plan(self, input, target):
        input, target = self._input(input), self._input(target)
        _binary_f1_score_update_input_check(input, target)
        return UpdatePlan(
            _binary_f1_score_update_jit,
            ("num_tp", "num_label", "num_prediction"),
            (input, target),
            (float(self.threshold),),
            masked_kernel=_binary_f1_score_update_masked,
            batch_axes=(("batch",), ("batch",)),
        )

    def update(self, input, target) -> "BinaryF1Score":
        return self._apply_update_plan(self._update_plan(input, target))
