"""Zero-stall sync plane: background collectives, versioned snapshots &
bounded-staleness reads (ROADMAP item 2; ISSUE 16).

``sync_and_compute`` stalls its caller for a full collective round trip;
at serving scale that stall IS the tail latency. The FPGA-SmartNIC line
of work (arXiv:2204.10943) argues collectives belong off the critical
path, and Prime CCL (arXiv:2505.14065) runs communication on a dedicated
background plane. :class:`SyncPlane` brings that posture to eager metric
sync, in three pieces:

**Versioned snapshot publication** (serving thread, zero collectives,
zero host syncs). :meth:`SyncPlane.publish` captures the live
collection's trimmed sync payloads — jax arrays are immutable, so the
capture is O(#states) reference snapshots (the PR 6 ``_clone_state`` /
``state_dict`` discipline), never a device sync — and swaps ONE
fully-built immutable record under the plane lock. Readers either see
the previous record or the new one, never a torn mix (pinned by
DeterministicScheduler interleavings in tests/metrics/test_syncplane.py).

**A background sync round** (plane thread, ``# tev: scope=syncplane``).
The thread wakes at ``interval``, loads the freshest published payload
into fresh clones, and runs the UNCHANGED eager sync protocol
(``toolkit.get_synced_metric_collection``) on a DEDICATED communicator:
``group.new_subgroup(all ranks)`` wrapped in a
:class:`~torcheval_tpu.resilience.ResilientGroup`, generalizing the PR 4
elastic writer-comm pattern — the plane's collective sequence can never
interleave with main-thread syncs on the parent group, and every round
rides the full resilience policy surface (deadline / retries / quorum
degradation / survivor re-formation). Rounds rendezvous across ranks
like any collective, so the planes of a world pace each other; a dead
rank costs one bounded, policy-degraded round, not a wedged thread.

**Bounded-staleness reads** (any thread, non-blocking).
:meth:`SyncPlane.read` / :meth:`SyncPlane.compute` — and the toolkit /
federation entry points' ``plane=`` form — return the freshest merged
snapshot, stamped with the same staleness vocabulary PR 14 defined for
regions: the read's ``sync_provenance`` carries ``version`` (which merge
round it observed), ``rounds_behind`` (publish generations the serving
state has advanced past it), and ``wall_age_seconds``. One staleness
model end to end, intra-region and WAN.

Correctness contract: a bounded-staleness read at version V is
bit-identical to a blocking ``sync_and_compute`` over the states
published for V (the ThreadWorld-4 oracle pin). ``Metric.reset()`` /
``load_state_dict`` bump the metric's ``_state_epoch``; a snapshot
captured at an older epoch is DISCARDED at read time (a post-reset read
must never serve pre-reset merged values) — the read falls back to a
local clone with degraded, version-0 provenance until the next
publish/round covers the new state.

Observability: each round records a
:class:`~torcheval_tpu.obs.events.PlaneSyncEvent` (plus the eager
protocol's own ``SyncEvent``/flight records — the stall watchdog
therefore covers a stalled plane round like any other collective), an
armed plane exports a ``syncplane/*`` counter source, and
``/healthz`` degrades to ``stale-plane`` when the freshest merged
snapshot ages past ``stale_after`` (``obs.server.healthz_payload``).

::

    plane = SyncPlane({"acc": acc, "loss": loss}, group, interval=2.0)
    for batch in loader:
        acc.update(*batch)           # never blocks: zero collectives
        plane.publish()              # O(#states) reference snapshot
        values = plane.compute()     # freshest merged, with staleness
    plane.close()

See docs/fault-tolerance.md, "Zero-stall sync plane".
"""

from __future__ import annotations

import contextlib
import logging
import threading
import time
import warnings
from typing import Any, Dict, Iterator, NamedTuple, Optional, Sequence, Union

from torcheval_tpu.distributed import (
    LocalReplicaGroup,
    ProcessGroup,
    default_process_group,
)
from torcheval_tpu.metrics.metric import Metric
from torcheval_tpu.obs.recorder import RECORDER as _OBS
from torcheval_tpu.resilience import ResilientGroup, SyncProvenance

__all__ = ["SyncPlane", "current_plane"]

_logger: logging.Logger = logging.getLogger(__name__)


class _Published(NamedTuple):
    """One immutable published-state record (swapped as a whole)."""

    generation: int
    states: Dict[str, Dict[str, Any]]  # {metric: trimmed sync payload}
    epochs: Dict[str, int]  # {metric: _state_epoch at capture}
    wall: float


class _Merged(NamedTuple):
    """One immutable merged-snapshot record (swapped as a whole)."""

    version: int
    generation: int  # publish generation this round consumed
    metrics: Dict[str, Metric]  # merged clones — treated as immutable
    base: SyncProvenance  # the round's sync provenance (staleness unset)
    epochs: Dict[str, int]
    wall: float


class SyncPlane:
    """Asynchronous eval plane for one ``{name: Metric}`` collection.

    Args:
        metrics: the LIVE serving collection (or a single
            :class:`Metric`, wrapped like the toolkit does). The plane
            holds references: reads validate published snapshots against
            these instances' ``_state_epoch``.
        process_group: the rank world (default
            ``distributed.default_process_group()``). The plane derives
            a DEDICATED whole-world subgroup from it; per-replica
            ``LocalReplicaGroup`` worlds are not supported (one plane
            per logical rank, like :class:`~torcheval_tpu.elastic.ElasticSession`).
        interval: background round cadence in seconds; ``None`` (default)
            arms no thread — call :meth:`run_round` yourself (tests,
            deterministic loops, callers with their own scheduler).
        timeout / retries / policy / quorum / reform_after: the
            :class:`~torcheval_tpu.resilience.ResilientGroup` knobs for
            the plane's communicator (defaults from ``config``, like any
            sync). A degrading policy is strongly recommended for an
            armed plane: it bounds what a dead rank can cost a round.
        history: merged snapshot versions retained for
            :meth:`snapshot_at` (federation version-agreement reads).
        stale_after: ``/healthz`` degradation bound in seconds — the
            plane reports stale once its freshest merged snapshot (or,
            before the first round, its arm time) ages past this.
            Default: ``10 * interval`` when a thread is armed, else
            disabled; ``0`` disables explicitly.
    """

    def __init__(
        self,
        metrics: Union[Metric, Dict[str, Metric]],
        process_group: Optional[ProcessGroup] = None,
        *,
        interval: Optional[float] = None,
        timeout: Optional[float] = None,
        retries: Optional[int] = None,
        policy: Optional[str] = None,
        quorum: Optional[float] = None,
        reform_after: Optional[int] = None,
        history: int = 4,
        stale_after: Optional[float] = None,
    ) -> None:
        if isinstance(metrics, Metric):
            metrics = {"_metric": metrics}
        if not metrics or not all(
            isinstance(m, Metric) for m in metrics.values()
        ):
            raise TypeError(
                "metrics must be a Metric or a non-empty {name: Metric} "
                "dict holding this rank's live metrics"
            )
        self.metrics: Dict[str, Metric] = dict(metrics)
        group = (
            process_group
            if process_group is not None
            else default_process_group()
        )
        if isinstance(group.unwrap(), LocalReplicaGroup):
            raise TypeError(
                "SyncPlane syncs one rank's metrics per plane; a "
                "LocalReplicaGroup's per-replica metric lists are not "
                "supported — run one plane per logical rank"
            )
        if not group.is_member:
            raise ValueError(
                "this process is not a member of the given process group"
            )
        self._group = group  # tev: disable=unguarded-state -- reassigned only by reform() under the _round_lock quiesce fence (no round in flight across the swap); every other write is __init__
        # kept so a failover reform can derive a fresh dedicated
        # communicator for the survivor world with IDENTICAL semantics
        self._comm_knobs: Dict[str, Any] = dict(
            timeout=timeout,
            retries=retries,
            policy=policy,
            quorum=quorum,
            reform_after=reform_after,
        )
        self._comm: ProcessGroup = self._dedicated_comm(**self._comm_knobs)  # tev: disable=unguarded-state -- reassigned only by reform() under the _round_lock quiesce fence; the round thread reads it inside the same fence
        if interval is not None and interval <= 0:
            raise ValueError(f"interval must be > 0 seconds, got {interval}")
        self.interval = interval
        if history < 1:
            raise ValueError(f"history must be >= 1, got {history}")
        self.history = int(history)
        if stale_after is None:
            stale_after = 10.0 * interval if interval is not None else 0.0
        self.stale_after = float(stale_after)
        # templates frozen at construction: each round clones these and
        # loads the published payload over them, so a round never reads
        # the LIVE metrics (the serving thread owns those)
        import copy as _copy

        self._templates: Dict[str, Metric] = {
            name: _copy.deepcopy(m).reset() for name, m in self.metrics.items()
        }
        self._lock = threading.Lock()
        self._published: Optional[_Published] = None  # tev: guarded-by=_lock
        self._publish_gen = 0  # tev: guarded-by=_lock
        self._merged: Optional[_Merged] = None  # tev: guarded-by=_lock
        self._version = 0  # tev: guarded-by=_lock
        self._history: Dict[int, _Merged] = {}  # tev: guarded-by=_lock
        self.rounds = 0  # tev: guarded-by=_lock
        self.degraded_rounds = 0  # tev: guarded-by=_lock
        self.round_errors = 0  # tev: guarded-by=_lock
        self.last_error: Optional[str] = None  # tev: guarded-by=_lock
        self.reads = 0  # tev: guarded-by=_lock
        self.cold_reads = 0  # tev: guarded-by=_lock
        # quiesce fence: every round holds it for the round's duration;
        # holders (elastic snapshot/restore) exclude rounds, not reads
        self._round_lock = threading.Lock()  # tev: disable=bare-lock -- serializes round EXECUTION (the quiesce fence), not data: every shared field is bound to _lock; binding a field here would misdescribe the contract
        self._stop = threading.Event()
        self._armed_wall = time.time()
        self._thread: Optional[threading.Thread] = None
        self._closed = False  # tev: disable=unguarded-state -- caller-thread lifecycle flag (close() is caller API); the round thread only reads it to exit early, and a stale read costs one bounded extra round, never a hang
        if interval is not None:
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="torcheval-syncplane"
            )
            self._thread.start()
        self._arm()

    # ------------------------------------------------------------- plumbing

    def _dedicated_comm(self, **knobs: Any) -> ProcessGroup:
        """The communicator plane rounds run on: a dedicated whole-world
        subgroup (own collective sequence — background rounds can never
        pair off against main-thread syncs on the parent group), wrapped
        with the plane's resilience knobs. Generalizes the PR 4 elastic
        writer-comm pattern."""
        try:
            ded = self._group.new_subgroup(range(self._group.world_size))
        except NotImplementedError:
            ded = self._group
            if self._group.world_size > 1:
                warnings.warn(
                    f"{type(self._group).__name__} cannot scope a dedicated "
                    "plane communicator (no new_subgroup): do not issue "
                    "metric-sync collectives on this group while a plane "
                    "round may be in flight — cross-thread collectives on "
                    "one group can pair off out of order across ranks",
                    RuntimeWarning,
                )
        if isinstance(ded, ResilientGroup):
            return ded
        return ResilientGroup(ded, **knobs)

    @property
    def world_size(self) -> int:
        return self._comm.world_size

    @property
    def ranks(self) -> Sequence[int]:
        """Global ranks of the plane's world (the parent group's)."""
        return tuple(self._group.ranks)

    @property
    def rank(self) -> int:
        return self._comm.rank

    @property
    def policy(self) -> str:
        return getattr(self._comm, "degradation_policy", "raise")

    @property
    def armed(self) -> bool:
        """Whether a background round thread is running."""
        return self._thread is not None and self._thread.is_alive()

    @property
    def version(self) -> int:
        """Version of the freshest merged snapshot (0 = none yet)."""
        with self._lock:
            return self._version if self._merged is not None else 0

    @property
    def publishes(self) -> int:
        """Publish generations issued so far."""
        with self._lock:
            return self._publish_gen

    # -------------------------------------------------------------- publish

    def publish(self) -> int:
        """Capture the live collection's sync payload and swap it in as
        the newest published record (serving thread; zero collectives,
        zero host syncs — jax arrays are immutable, so this is O(#states)
        reference snapshots). Returns the publish generation."""
        self._check_open()
        for m in self.metrics.values():
            m._prepare_for_merge_state()
        states = {
            name: m._sync_state_dict() for name, m in self.metrics.items()
        }
        epochs = {
            name: m._state_epoch for name, m in self.metrics.items()
        }
        record = _Published(0, states, epochs, time.time())
        with self._lock:
            self._publish_gen += 1
            # the record is fully built before this single-reference
            # swap: a concurrent reader sees the old record or this one,
            # never a torn mix
            self._published = record._replace(generation=self._publish_gen)
            return self._publish_gen

    # --------------------------------------------------------------- rounds

    def run_round(self) -> Optional[int]:
        """Run ONE sync round now (every rank's plane must run rounds in
        step — the round is a collective). The armed thread calls this on
        its own cadence; manual planes (``interval=None``) call it from
        their scheduler or tests. Returns the new merged version, or
        ``None`` when nothing has been published yet."""
        self._check_open()
        with self._round_lock:
            return self._round()  # tev: disable=blocking-under-lock -- the quiesce fence intentionally spans the round's collectives (that is its contract: no round in flight while held); _round_lock is a leaf — the collective path takes only _lock briefly and never _round_lock, and the communicator's deadline bounds the wait

    def _round(self) -> Optional[int]:
        from torcheval_tpu.metrics.toolkit import (
            clone_metric,
            get_synced_metric_collection,
        )

        with self._lock:
            pub = self._published
        if self._comm.world_size > 1:
            # readiness agreement: a rank with nothing published (fresh
            # plane, or just invalidated by an elastic restore) must not
            # silently sit out while its peers rendezvous on the state
            # sync — every rank gathers its publish generation first and
            # the round proceeds only when ALL ranks have one (the tiny
            # gather rides the plane's own communicator and policy, so a
            # DEAD rank still degrades instead of hanging)
            flags = self._comm.allgather_object(
                int(pub.generation) if pub is not None else 0
            )
            if any(int(f) == 0 for f in flags):
                return None
        if pub is None:
            return None
        t0 = time.monotonic()
        coll: Dict[str, Metric] = {}
        for name, template in self._templates.items():
            clone = clone_metric(template)
            clone.load_state_dict(pub.states[name], strict=False)
            coll[name] = clone
        if self._comm.world_size == 1:
            # world-of-one fast path: the local payload IS the merged
            # state; skip the toolkit's per-round world-1 warning
            provenance = SyncProvenance(
                ranks=(self._comm.rank,),
                world_size=1,
                degraded=False,
                policy=self.policy,
            )
            synced = coll
            for m in synced.values():
                m.sync_provenance = provenance
        else:
            synced = get_synced_metric_collection(coll, self._comm)
            provenance = next(iter(synced.values())).sync_provenance
        seconds = time.monotonic() - t0
        now = time.time()
        with self._lock:
            self._version += 1
            record = _Merged(
                self._version, pub.generation, synced, provenance, pub.epochs,
                now,
            )
            self._merged = record
            self._history[record.version] = record
            for old in [
                v for v in self._history if v <= record.version - self.history
            ]:
                del self._history[old]
            self.rounds += 1
            if provenance.degraded:
                self.degraded_rounds += 1
        if _OBS.enabled:
            from torcheval_tpu.obs.events import PlaneSyncEvent

            _OBS.record(
                PlaneSyncEvent(
                    rank=self._comm.rank,
                    version=record.version,
                    generation=record.generation,
                    ranks=provenance.ranks,
                    world_size=provenance.world_size,
                    degraded=provenance.degraded,
                    policy=provenance.policy,
                    reformed=provenance.reformed,
                    metrics=len(synced),
                    seconds=seconds,
                )
            )
        return record.version

    def _loop(self) -> None:  # tev: scope=syncplane
        while not self._stop.wait(self.interval):
            try:
                self.run_round()
            except Exception as e:  # noqa: BLE001 — the plane outlives a failed round
                if self._closed:
                    break  # a round racing close() is shutdown, not failure
                with self._lock:
                    self.round_errors += 1
                    self.last_error = f"{type(e).__name__}: {e}"
                _logger.warning("sync plane round failed: %s", e)
                if _OBS.enabled:
                    from torcheval_tpu.obs.events import PlaneSyncEvent

                    _OBS.record(
                        PlaneSyncEvent(
                            rank=self._comm.rank,
                            policy=self.policy,
                            error=f"{type(e).__name__}: {e}",
                        )
                    )

    # ---------------------------------------------------------------- reads

    def read(
        self, names: Optional[Sequence[str]] = None
    ) -> Dict[str, Metric]:
        """Freshest merged snapshot as ``{name: Metric}`` clones, each
        carrying bounded-staleness ``sync_provenance`` (non-blocking; no
        collective, ever). A snapshot invalidated by ``reset()`` /
        ``load_state_dict`` on a live metric — or a plane that has not
        completed a round — falls back to LOCAL clones of the live
        metrics with degraded, version-0 provenance."""
        from torcheval_tpu.metrics.toolkit import clone_metric

        self._check_open()
        selected = self._select(names)
        with self._lock:
            record = self._merged
            generation = self._publish_gen
        valid = record is not None and all(
            record.epochs.get(name) == self.metrics[name]._state_epoch
            for name in selected
        )
        if not valid:
            provenance = SyncProvenance(
                ranks=(self.rank,),
                world_size=self.world_size,
                degraded=self.world_size > 1,
                policy=self.policy,
            )
            out = {}
            for name in selected:
                clone = clone_metric(self.metrics[name])
                clone.sync_provenance = provenance
                out[name] = clone
            with self._lock:
                self.cold_reads += 1
            return out
        provenance = record.base._replace(
            version=record.version,
            rounds_behind=max(0, generation - record.generation),
            wall_age_seconds=max(0.0, time.time() - record.wall),
        )
        out = {}
        for name in selected:
            clone = clone_metric(record.metrics[name])
            clone.sync_provenance = provenance
            out[name] = clone
        with self._lock:
            self.reads += 1
        return out

    def compute(
        self, names: Optional[Sequence[str]] = None
    ) -> Dict[str, Any]:
        """``{name: value}`` computed from :meth:`read` (non-blocking)."""
        return {name: m.compute() for name, m in self.read(names).items()}

    def read_metric(self, metric: Union[str, Metric]) -> Metric:
        """Single-metric :meth:`read`, addressed by registered name or by
        the live instance itself (the toolkit's ``plane=`` path)."""
        name = self._name_of(metric)
        return self.read([name])[name]

    def read_collection(
        self, metrics: Dict[str, Metric]
    ) -> Dict[str, Metric]:
        """Collection :meth:`read` for ``sync_and_compute_collection
        (plane=...)``: every entry must be the SAME live instance the
        plane was built over under the SAME name — snapshot invalidation
        is validated against those instances' state epochs, so a
        look-alike collection would silently skip the validation."""
        for name, m in metrics.items():
            if self.metrics.get(name) is not m:
                self._name_of(m)  # raises with the identity message
                raise ValueError(
                    f"metric {name!r} is registered on this plane under a "
                    "different name — pass the collection the plane was "
                    "built over"
                )
        return self.read(tuple(metrics))

    def snapshot_at(self, version: int) -> Optional[Dict[str, Metric]]:
        """The RETAINED merged collection at exactly ``version`` (shared,
        treat as immutable), or ``None`` when that version was never
        produced or already evicted (``history``)."""
        with self._lock:
            record = self._history.get(int(version))
        return None if record is None else dict(record.metrics)

    def retained(self) -> Dict[int, _Merged]:
        """One consistent copy of the retained merged-version records
        (records are immutable; the dict is the caller's). This is what
        ``federation.Federation.exchange(plane=...)`` reads BEFORE its
        version-agreement gather, so the version it advertises can never
        be evicted out from under the read by a concurrent round."""
        with self._lock:
            return dict(self._history)

    def _select(self, names: Optional[Sequence[str]]) -> Sequence[str]:
        if names is None:
            return tuple(self.metrics)
        unknown = [n for n in names if n not in self.metrics]
        if unknown:
            raise KeyError(
                f"metrics {unknown} are not registered on this plane "
                f"(registered: {sorted(self.metrics)})"
            )
        return tuple(names)

    def _name_of(self, metric: Union[str, Metric]) -> str:
        if isinstance(metric, str):
            if metric not in self.metrics:
                raise KeyError(
                    f"metric {metric!r} is not registered on this plane"
                )
            return metric
        for name, m in self.metrics.items():
            if m is metric:
                return name
        raise ValueError(
            "metric is not registered on this plane — pass the same live "
            "instance the plane was built over (snapshot validation is "
            "against that instance's state epoch)"
        )

    # ------------------------------------------------------------ lifecycle

    @contextlib.contextmanager
    def quiesce(self) -> Iterator[None]:
        """Hold rounds still: no plane round starts (or is in flight)
        while the context is held. Used by elastic snapshot/restore so a
        checkpoint never interleaves with a half-merged round."""
        with self._round_lock:
            yield

    def invalidate(self) -> None:
        """Drop every published and merged snapshot (elastic restore:
        the state just loaded replaces what any snapshot describes).
        Counters keep counting — versions never move backwards."""
        with self._lock:
            self._published = None
            self._merged = None
            self._history.clear()

    def reform(self, process_group: ProcessGroup) -> None:
        """Move the plane onto a new world (``failover.FailureDomain``
        reform: the survivor subgroup after a rank loss, or the full
        group again at rejoin). Holds the quiesce fence so no round is
        in flight across the swap, derives a fresh dedicated
        communicator with the SAME resilience knobs the plane was
        constructed with, and invalidates every snapshot — they describe
        a world that no longer exists. Barrier-free: the swap itself
        issues no collective (the new communicator's first rendezvous is
        the next round's readiness gather)."""
        if not process_group.is_member:
            raise ValueError(
                "this process is not a member of the new process group"
            )
        with self._round_lock:
            self._group = process_group
            self._comm = self._dedicated_comm(**self._comm_knobs)
            self.invalidate()

    def staleness(self) -> Dict[str, Any]:
        """The plane's staleness surface (healthz / counters): freshest
        ``version``, publish ``generation`` consumed vs issued
        (``rounds_behind``), merged-snapshot ``wall_age_seconds`` (-1
        before the first round), and the ``stale`` verdict."""
        now = time.time()
        with self._lock:
            record = self._merged
            generation = self._publish_gen
            out: Dict[str, Any] = {
                "version": record.version if record is not None else 0,
                "publishes": generation,
                "rounds_behind": (
                    max(0, generation - record.generation)
                    if record is not None
                    else generation
                ),
                "wall_age_seconds": (
                    round(max(0.0, now - record.wall), 3)
                    if record is not None
                    else -1.0
                ),
                "stale_after": self.stale_after,
            }
        basis = (
            now - self._armed_wall
            if record is None
            else now - record.wall
        )
        out["stale"] = bool(
            self.stale_after > 0
            and self.armed
            and basis > self.stale_after
        )
        return out

    def stale_for_healthz(self) -> bool:
        """True when the freshest merged snapshot (or, before the first
        round, the plane's arm time) has aged past ``stale_after`` — the
        ``/healthz`` ``stale-plane`` 503 condition. Always False for
        manual (unarmed) planes and when ``stale_after`` is 0."""
        return bool(self.staleness()["stale"])

    def _counter_source(self) -> Dict[str, Any]:
        with self._lock:
            out: Dict[str, Any] = {
                "rounds": self.rounds,
                "degraded_rounds": self.degraded_rounds,
                "round_errors": self.round_errors,
                "reads": self.reads,
                "cold_reads": self.cold_reads,
                "armed": int(self._thread is not None),
            }
        out.update(
            (k, v)
            for k, v in self.staleness().items()
            if k != "stale_after"
        )
        out["stale"] = int(out["stale"])
        return out

    def _arm(self) -> None:
        global _CURRENT
        with _CURRENT_LOCK:
            _CURRENT = self
        from torcheval_tpu.obs.counters import default_registry

        default_registry().register("syncplane", self._counter_source)

    def close(self) -> None:
        """Stop the round thread (bounded join — the communicator's
        deadline bounds a round in flight) and disarm. Idempotent."""
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            deadline = getattr(self._comm, "timeout", None)
            retries = getattr(self._comm, "retries", 0) or 0
            grace = (
                (deadline or 0.0) * (1 + retries) + 5.0
                if deadline is not None
                else 30.0
            )
            thread.join(timeout=grace)
            if thread.is_alive():
                warnings.warn(
                    "sync plane thread did not stop within its deadline "
                    "budget; leaving the daemon thread behind",
                    RuntimeWarning,
                )
        global _CURRENT
        was_current = False
        with _CURRENT_LOCK:
            if _CURRENT is self:
                _CURRENT = None
                was_current = True
        if was_current:
            from torcheval_tpu.obs.counters import default_registry

            default_registry().unregister("syncplane")

    def __enter__(self) -> "SyncPlane":
        return self

    def __exit__(self, exc_type: Any, exc: Any, tb: Any) -> None:
        self.close()

    def _check_open(self) -> None:
        if self._closed:
            raise RuntimeError("SyncPlane is closed")


_CURRENT: Optional[SyncPlane] = None  # tev: guarded-by=_CURRENT_LOCK
_CURRENT_LOCK = threading.Lock()


def current_plane() -> Optional[SyncPlane]:
    """The most recently armed, not-yet-closed plane (the ``/healthz``
    staleness probe's handle), or ``None``."""
    return _CURRENT  # tev: disable=guarded-field -- single-reference read, atomic under the GIL; the healthz probe tolerates a one-scrape-stale plane
