from torcheval_tpu.parallel.ring_attention import (
    dense_reference_attention,
    ring_attention,
)

__all__ = ["dense_reference_attention", "ring_attention"]
