"""Repo-root pytest config.

Must run before JAX initializes its backends: forces an 8-device virtual CPU
platform so multi-device sharding/sync tests run without TPU hardware
(the JAX analogue of the reference's multi-process gloo-on-localhost test
strategy, reference utils/test_utils/metric_class_tester.py:292-341).
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# Some images expose an experimental TPU plugin that wins default-backend even
# when tests want CPU; pin default placement to the virtual CPU mesh.
jax.config.update("jax_default_device", jax.devices("cpu")[0])
