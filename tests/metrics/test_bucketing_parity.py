"""Shape bucketing must never change a metric's value.

Every converted kernel's contract: padded rows contribute EXACTLY ZERO to
every state, so a ragged stream under ``config.shape_bucketing()`` computes
the same result as the unbucketed path. For counting metrics (accuracy /
precision / recall / F1 / confusion matrix / binned curves) the states are
sums of 0/1 indicators — exact in float32 regardless of association — so
parity is asserted BIT-IDENTICAL. Real-valued accumulators (MSE, R2,
perplexity) append zeros to the reduced array, which can change XLA's
reduction tree, so those assert to float32 resolution (rtol 1e-6).

The same streams are also checked against the reference oracle where the
/root/reference mount exists (tests/ref_oracle.py).
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from torcheval_tpu import config
from torcheval_tpu import metrics as M
from torcheval_tpu.metrics._bucket import MIN_BUCKET, bucket_bound, bucket_length
from torcheval_tpu.metrics.toolkit import update_collection

RNG = np.random.default_rng(17)
C = 6
SIZES = [5, 33, 64, 100, 13, 1]  # ragged stream incl. an exact bucket size


def _cls_batch(n):
    return (
        RNG.uniform(size=(n, C)).astype(np.float32),
        np.asarray(RNG.integers(0, C, size=(n,))),
    )


def _bin_batch(n):
    return (
        RNG.uniform(size=(n,)).astype(np.float32),
        np.asarray(RNG.integers(0, 2, size=(n,))),
    )


def _reg_batch(n):
    return (
        RNG.normal(size=(n,)).astype(np.float32),
        RNG.normal(size=(n,)).astype(np.float32),
    )


def _ml_batch(n):
    return (
        RNG.uniform(size=(n, C)).astype(np.float32),
        np.asarray(RNG.integers(0, 2, size=(n, C))),
    )


def _ppl_batch(n):
    return (
        RNG.normal(size=(2, n, 16)).astype(np.float32),
        np.asarray(RNG.integers(0, 16, size=(2, n))),
    )


def _run_stream(ctor, batches, bucketed):
    metric = ctor()
    if bucketed:
        with config.shape_bucketing():
            for args in batches:
                metric.update(*args)
    else:
        for args in batches:
            metric.update(*args)
    return metric.compute()


def _flat(result):
    if isinstance(result, (tuple, list)):
        return np.concatenate([np.asarray(r).ravel() for r in result])
    return np.asarray(result)


EXACT_CASES = [
    ("MulticlassAccuracy", lambda: M.MulticlassAccuracy(), _cls_batch),
    (
        "MulticlassAccuracy_macro",
        lambda: M.MulticlassAccuracy(average="macro", num_classes=C),
        _cls_batch,
    ),
    (
        "MulticlassAccuracy_top2",
        lambda: M.MulticlassAccuracy(k=2),
        _cls_batch,
    ),
    ("BinaryAccuracy", lambda: M.BinaryAccuracy(), _bin_batch),
    (
        "MultilabelAccuracy_hamming",
        lambda: M.MultilabelAccuracy(criteria="hamming"),
        _ml_batch,
    ),
    (
        "TopKMultilabelAccuracy",
        lambda: M.TopKMultilabelAccuracy(criteria="overlap", k=2),
        _ml_batch,
    ),
    ("MulticlassPrecision", lambda: M.MulticlassPrecision(), _cls_batch),
    (
        "MulticlassPrecision_none",
        lambda: M.MulticlassPrecision(num_classes=C, average=None),
        _cls_batch,
    ),
    ("BinaryPrecision", lambda: M.BinaryPrecision(), _bin_batch),
    (
        "MulticlassRecall_weighted",
        lambda: M.MulticlassRecall(num_classes=C, average="weighted"),
        _cls_batch,
    ),
    ("BinaryRecall", lambda: M.BinaryRecall(), _bin_batch),
    (
        "MulticlassF1Score_macro",
        lambda: M.MulticlassF1Score(num_classes=C, average="macro"),
        _cls_batch,
    ),
    ("BinaryF1Score", lambda: M.BinaryF1Score(), _bin_batch),
    (
        "MulticlassConfusionMatrix",
        lambda: M.MulticlassConfusionMatrix(C),
        _cls_batch,
    ),
    ("BinaryConfusionMatrix", lambda: M.BinaryConfusionMatrix(), _bin_batch),
    (
        "BinaryBinnedPrecisionRecallCurve",
        lambda: M.BinaryBinnedPrecisionRecallCurve(threshold=9),
        _bin_batch,
    ),
    (
        "MulticlassBinnedPrecisionRecallCurve",
        lambda: M.MulticlassBinnedPrecisionRecallCurve(
            num_classes=C, threshold=7
        ),
        _cls_batch,
    ),
    (
        "MulticlassBinnedPRC_memory",
        lambda: M.MulticlassBinnedPrecisionRecallCurve(
            num_classes=C, threshold=7, optimization="memory"
        ),
        _cls_batch,
    ),
    (
        "MultilabelBinnedPrecisionRecallCurve",
        lambda: M.MultilabelBinnedPrecisionRecallCurve(
            num_labels=C, threshold=7
        ),
        _ml_batch,
    ),
    (
        "MultilabelBinnedPRC_memory",
        lambda: M.MultilabelBinnedPrecisionRecallCurve(
            num_labels=C, threshold=7, optimization="memory"
        ),
        _ml_batch,
    ),
]

CLOSE_CASES = [
    ("MeanSquaredError", lambda: M.MeanSquaredError(), _reg_batch),
    ("R2Score", lambda: M.R2Score(), _reg_batch),
    ("Perplexity", lambda: M.Perplexity(), _ppl_batch),
    ("Perplexity_ignore", lambda: M.Perplexity(ignore_index=3), _ppl_batch),
]


@pytest.mark.parametrize(
    "name,ctor,gen", EXACT_CASES, ids=[c[0] for c in EXACT_CASES]
)
def test_bucketed_equals_unbucketed_exact(name, ctor, gen):
    batches = [gen(n) for n in SIZES]
    plain = _flat(_run_stream(ctor, batches, bucketed=False))
    bucketed = _flat(_run_stream(ctor, batches, bucketed=True))
    np.testing.assert_array_equal(plain, bucketed)


@pytest.mark.parametrize(
    "name,ctor,gen", CLOSE_CASES, ids=[c[0] for c in CLOSE_CASES]
)
def test_bucketed_equals_unbucketed_close(name, ctor, gen):
    batches = [gen(n) for n in SIZES]
    plain = _flat(_run_stream(ctor, batches, bucketed=False))
    bucketed = _flat(_run_stream(ctor, batches, bucketed=True))
    np.testing.assert_allclose(plain, bucketed, rtol=1e-6, atol=1e-7)


def test_weighted_mse_masks_through_sample_weight():
    batches = [
        (*_reg_batch(n), RNG.uniform(0.5, 2.0, size=(n,)).astype(np.float32))
        for n in SIZES
    ]

    def run(bucketed):
        metric = M.MeanSquaredError()
        ctx = config.shape_bucketing() if bucketed else _null_ctx()
        with ctx:
            for x, t, w in batches:
                metric.update(x, t, sample_weight=w)
        return np.asarray(metric.compute())

    np.testing.assert_allclose(run(False), run(True), rtol=1e-6)


def _null_ctx():
    import contextlib

    return contextlib.nullcontext()


def test_device_array_inputs_bucket_too():
    """jax.Array inputs take the (trivially jitted) device-pad path; the
    values must still match exactly."""
    batches = [tuple(jnp.asarray(a) for a in _cls_batch(n)) for n in SIZES]
    plain = _flat(
        _run_stream(lambda: M.MulticlassAccuracy(), batches, bucketed=False)
    )
    bucketed = _flat(
        _run_stream(lambda: M.MulticlassAccuracy(), batches, bucketed=True)
    )
    np.testing.assert_array_equal(plain, bucketed)


def test_update_collection_bucketed_parity():
    """The fused-group path pads once per batch and must agree with the
    per-metric path."""
    def panel():
        return {
            "acc": M.MulticlassAccuracy(),
            "f1": M.MulticlassF1Score(num_classes=C, average="macro"),
            "cm": M.MulticlassConfusionMatrix(C),
        }

    batches = [_cls_batch(n) for n in SIZES]
    plain, bucketed = panel(), panel()
    for args in batches:
        update_collection(plain, *args)
    with config.shape_bucketing():
        for args in batches:
            update_collection(bucketed, *args)
    for key in plain:
        np.testing.assert_array_equal(
            np.asarray(plain[key].compute()),
            np.asarray(bucketed[key].compute()),
            err_msg=key,
        )


def test_bucket_length_and_bound():
    assert bucket_length(1) == MIN_BUCKET
    assert bucket_length(MIN_BUCKET) == MIN_BUCKET
    assert bucket_length(MIN_BUCKET + 1) == 2 * MIN_BUCKET
    assert bucket_length(1000) == 1024
    assert bucket_length(1024) == 1024
    # bound counts the distinct buckets sizes in [1, max] can produce
    assert bucket_bound(1024) == len(
        {bucket_length(n) for n in range(1, 1025)}
    )


def test_input_validation_still_raises_under_bucketing():
    """Host (numpy) inputs flow through the same shape validation."""
    m = M.MulticlassAccuracy()
    x, _ = _cls_batch(8)
    _, t = _cls_batch(9)
    with config.shape_bucketing():
        with pytest.raises(ValueError, match="first dimension"):
            m.update(x, t)


def test_oracle_parity_bucketed_stream():
    """Bucketed ragged streams against the reference torcheval oracle
    (skips where /root/reference is not mounted)."""
    from tests.ref_oracle import load_reference_metrics

    ref_m, _ = load_reference_metrics()
    if ref_m is None:
        pytest.skip("reference oracle unavailable")
    import torch

    batches = [_cls_batch(n) for n in SIZES]

    ours = M.MulticlassAccuracy()
    with config.shape_bucketing():
        for x, t in batches:
            ours.update(x, t)
    ref = ref_m.MulticlassAccuracy()
    for x, t in batches:
        ref.update(torch.tensor(x), torch.tensor(t))
    np.testing.assert_allclose(
        np.asarray(ours.compute()), np.asarray(ref.compute()), rtol=1e-6
    )

    ours_f1 = M.MulticlassF1Score(num_classes=C, average="macro")
    with config.shape_bucketing():
        for x, t in batches:
            ours_f1.update(x, t)
    ref_f1 = ref_m.MulticlassF1Score(num_classes=C, average="macro")
    for x, t in batches:
        ref_f1.update(torch.tensor(x), torch.tensor(t))
    np.testing.assert_allclose(
        np.asarray(ours_f1.compute()), np.asarray(ref_f1.compute()),
        rtol=1e-6,
    )
