"""Curve-family class metric tests (AUROC/AUPRC/PRC/RecallAtFixedPrecision)
vs the reference oracle, via the shared harness."""

import jax.numpy as jnp
import numpy as np
import pytest
import torch
from sklearn.metrics import roc_auc_score

from tests.ref_oracle import load_reference_metrics
from torcheval_tpu.metrics import (
    BinaryAUPRC,
    BinaryAUROC,
    BinaryPrecisionRecallCurve,
    BinaryRecallAtFixedPrecision,
    MulticlassAUPRC,
    MulticlassAUROC,
    MulticlassPrecisionRecallCurve,
    MultilabelAUPRC,
    MultilabelPrecisionRecallCurve,
    MultilabelRecallAtFixedPrecision,
)
from torcheval_tpu.metrics import functional as F
from torcheval_tpu.utils.test_utils.metric_class_tester import (
    MetricClassTester,
    assert_result_close,
)

REF_M, REF_F = load_reference_metrics()
RNG = np.random.default_rng(21)
N_UP, BATCH, C = 8, 10, 4


def _to_np(x):
    if isinstance(x, (list, tuple)):
        return [np.asarray(v) for v in x]
    return np.asarray(x)


def _ref_class_result(metric, update_args):
    for args in update_args:
        metric.update(*[torch.tensor(np.asarray(a)) for a in args])
    out = metric.compute()
    if isinstance(out, tuple):
        return tuple(_to_np(v) for v in out)
    return _to_np(out)


class TestBinaryAUROC(MetricClassTester):
    def test_binary_auroc_with_ties_and_weights(self):
        inputs = [
            RNG.choice([0.1, 0.4, 0.7, 0.9], size=BATCH).astype(np.float32)
            for _ in range(N_UP)
        ]
        targets = [RNG.integers(0, 2, BATCH) for _ in range(N_UP)]
        ref = REF_M.BinaryAUROC()
        for x, t in zip(inputs, targets):
            ref.update(torch.tensor(x), torch.tensor(t))
        self.run_class_implementation_tests(
            metric=BinaryAUROC(),
            state_names={"inputs", "targets", "weights", "_num_samples"},
            update_kwargs={"input": inputs, "target": targets},
            compute_result=np.asarray(ref.compute()),
        )

    def test_multi_task(self):
        inputs = [RNG.uniform(size=(2, BATCH)).astype(np.float32) for _ in range(N_UP)]
        targets = [RNG.integers(0, 2, (2, BATCH)) for _ in range(N_UP)]
        expected = _ref_class_result(
            REF_M.BinaryAUROC(num_tasks=2), list(zip(inputs, targets))
        )
        self.run_class_implementation_tests(
            metric=BinaryAUROC(num_tasks=2),
            state_names={"inputs", "targets", "weights", "_num_samples"},
            update_kwargs={"input": inputs, "target": targets},
            compute_result=expected,
        )

    def test_vs_sklearn(self):
        x = RNG.uniform(size=200).astype(np.float32)
        t = RNG.integers(0, 2, 200)
        assert_result_close(
            F.binary_auroc(jnp.asarray(x), jnp.asarray(t)), roc_auc_score(t, x)
        )

    def test_degenerate_all_positive(self):
        out = F.binary_auroc(jnp.array([0.2, 0.8]), jnp.array([1, 1]))
        assert float(out) == 0.5

    def test_fused_approximate_kernel(self):
        # without ties the approximation is exact
        x = np.sort(RNG.uniform(size=50).astype(np.float32))
        t = RNG.integers(0, 2, 50)
        exact = F.binary_auroc(jnp.asarray(x), jnp.asarray(t))
        approx = F.binary_auroc(jnp.asarray(x), jnp.asarray(t), use_fused=True)
        assert_result_close(exact, approx)

    def test_input_checks(self):
        with pytest.raises(ValueError, match="same shape"):
            F.binary_auroc(jnp.ones(3), jnp.ones(4))
        with pytest.raises(ValueError, match="num_tasks = 1"):
            F.binary_auroc(jnp.ones((2, 3)), jnp.ones((2, 3)))


class TestMulticlassAUROC(MetricClassTester):
    @pytest.mark.parametrize("average", ["macro", None])
    def test_multiclass_auroc(self, average):
        inputs = [
            RNG.uniform(size=(BATCH, C)).astype(np.float32) for _ in range(N_UP)
        ]
        targets = [RNG.integers(0, C, BATCH) for _ in range(N_UP)]
        expected = _ref_class_result(
            REF_M.MulticlassAUROC(num_classes=C, average=average),
            list(zip(inputs, targets)),
        )
        self.run_class_implementation_tests(
            metric=MulticlassAUROC(num_classes=C, average=average),
            state_names={"inputs", "targets", "_num_samples"},
            update_kwargs={"input": inputs, "target": targets},
            compute_result=expected,
        )

    def test_param_checks(self):
        with pytest.raises(ValueError, match="`average`"):
            MulticlassAUROC(num_classes=3, average="weighted")
        with pytest.raises(ValueError, match="at least 2"):
            MulticlassAUROC(num_classes=1)


class TestAUPRC(MetricClassTester):
    def test_binary_auprc(self):
        inputs = [RNG.uniform(size=BATCH).astype(np.float32) for _ in range(N_UP)]
        targets = [RNG.integers(0, 2, BATCH) for _ in range(N_UP)]
        expected = _ref_class_result(REF_M.BinaryAUPRC(), list(zip(inputs, targets)))
        self.run_class_implementation_tests(
            metric=BinaryAUPRC(),
            state_names={"inputs", "targets", "_num_samples"},
            update_kwargs={"input": inputs, "target": targets},
            compute_result=expected,
        )

    @pytest.mark.parametrize("average", ["macro", None])
    def test_multiclass_auprc(self, average):
        inputs = [
            RNG.uniform(size=(BATCH, C)).astype(np.float32) for _ in range(N_UP)
        ]
        targets = [RNG.integers(0, C, BATCH) for _ in range(N_UP)]
        expected = _ref_class_result(
            REF_M.MulticlassAUPRC(num_classes=C, average=average),
            list(zip(inputs, targets)),
        )
        self.run_class_implementation_tests(
            metric=MulticlassAUPRC(num_classes=C, average=average),
            state_names={"inputs", "targets", "_num_samples"},
            update_kwargs={"input": inputs, "target": targets},
            compute_result=expected,
        )

    def test_multilabel_auprc(self):
        inputs = [
            RNG.uniform(size=(BATCH, 3)).astype(np.float32) for _ in range(N_UP)
        ]
        targets = [RNG.integers(0, 2, (BATCH, 3)) for _ in range(N_UP)]
        expected = _ref_class_result(
            REF_M.MultilabelAUPRC(num_labels=3), list(zip(inputs, targets))
        )
        self.run_class_implementation_tests(
            metric=MultilabelAUPRC(num_labels=3),
            state_names={"inputs", "targets", "_num_samples"},
            update_kwargs={"input": inputs, "target": targets},
            compute_result=expected,
        )


class TestPrecisionRecallCurve(MetricClassTester):
    def test_binary_prc(self):
        inputs = [RNG.uniform(size=BATCH).astype(np.float32) for _ in range(N_UP)]
        targets = [RNG.integers(0, 2, BATCH) for _ in range(N_UP)]
        expected = _ref_class_result(
            REF_M.BinaryPrecisionRecallCurve(), list(zip(inputs, targets))
        )
        self.run_class_implementation_tests(
            metric=BinaryPrecisionRecallCurve(),
            state_names={"inputs", "targets", "_num_samples"},
            update_kwargs={"input": inputs, "target": targets},
            compute_result=expected,
        )

    def test_multiclass_prc(self):
        inputs = [
            RNG.uniform(size=(BATCH, C)).astype(np.float32) for _ in range(N_UP)
        ]
        targets = [RNG.integers(0, C, BATCH) for _ in range(N_UP)]
        expected = _ref_class_result(
            REF_M.MulticlassPrecisionRecallCurve(num_classes=C),
            list(zip(inputs, targets)),
        )
        self.run_class_implementation_tests(
            metric=MulticlassPrecisionRecallCurve(num_classes=C),
            state_names={"inputs", "targets", "_num_samples"},
            update_kwargs={"input": inputs, "target": targets},
            compute_result=expected,
        )

    def test_multilabel_prc(self):
        inputs = [
            RNG.uniform(size=(BATCH, 3)).astype(np.float32) for _ in range(N_UP)
        ]
        targets = [RNG.integers(0, 2, (BATCH, 3)) for _ in range(N_UP)]
        expected = _ref_class_result(
            REF_M.MultilabelPrecisionRecallCurve(num_labels=3),
            list(zip(inputs, targets)),
        )
        self.run_class_implementation_tests(
            metric=MultilabelPrecisionRecallCurve(num_labels=3),
            state_names={"inputs", "targets", "_num_samples"},
            update_kwargs={"input": inputs, "target": targets},
            compute_result=expected,
        )

    def test_no_positive_examples_recall_is_one(self):
        p, r, t = F.binary_precision_recall_curve(
            jnp.array([0.3, 0.6]), jnp.array([0, 0])
        )
        assert np.all(np.asarray(r)[:-1] == 1.0)


class TestRecallAtFixedPrecision(MetricClassTester):
    def test_binary(self):
        inputs = [RNG.uniform(size=BATCH).astype(np.float32) for _ in range(N_UP)]
        targets = [RNG.integers(0, 2, BATCH) for _ in range(N_UP)]
        expected = _ref_class_result(
            REF_M.BinaryRecallAtFixedPrecision(min_precision=0.5),
            list(zip(inputs, targets)),
        )
        self.run_class_implementation_tests(
            metric=BinaryRecallAtFixedPrecision(min_precision=0.5),
            state_names={"inputs", "targets", "_num_samples"},
            update_kwargs={"input": inputs, "target": targets},
            compute_result=expected,
        )

    def test_multilabel(self):
        inputs = [
            RNG.uniform(size=(BATCH, 3)).astype(np.float32) for _ in range(N_UP)
        ]
        targets = [RNG.integers(0, 2, (BATCH, 3)) for _ in range(N_UP)]
        expected = _ref_class_result(
            REF_M.MultilabelRecallAtFixedPrecision(num_labels=3, min_precision=0.4),
            list(zip(inputs, targets)),
        )
        self.run_class_implementation_tests(
            metric=MultilabelRecallAtFixedPrecision(num_labels=3, min_precision=0.4),
            state_names={"inputs", "targets", "_num_samples"},
            update_kwargs={"input": inputs, "target": targets},
            compute_result=expected,
        )

    def test_reference_docstring_case(self):
        r, t = F.binary_recall_at_fixed_precision(
            jnp.array([0.1, 0.4, 0.6, 0.6, 0.6, 0.35, 0.8]),
            jnp.array([0, 0, 1, 1, 1, 1, 1]),
            min_precision=0.5,
        )
        assert float(r) == 1.0
        assert float(t) == pytest.approx(0.35)

    def test_min_precision_validation(self):
        with pytest.raises(ValueError, match="min_precision"):
            BinaryRecallAtFixedPrecision(min_precision=1.5)
