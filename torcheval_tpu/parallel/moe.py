"""Expert parallelism: top-1 (Switch-style) MoE dispatch over a mesh axis.

Experts are sharded one-per-device along an ``ep`` mesh axis; tokens are
sharded over the same axis. Each device routes its local tokens with a
softmax gate, packs them into a fixed-capacity ``(E, C, d)`` dispatch
buffer (static shapes — the TPU-idiomatic capacity formulation: tokens past
an expert's capacity are dropped, their output is zero), exchanges buffers
with one ``lax.all_to_all`` over ICI, applies its resident expert FFN — a
single large MXU matmul over all received tokens — and returns results with
a second ``all_to_all``. Gate-probability weighting happens at the source
device, so the combine is a gather, not a collective.

The reference has no expert parallelism (it is a metrics library;
SURVEY.md section 5.7) — this primitive exists so the *evaluation* stack
(flagship model forward + metric updates, see ``__graft_entry__``) covers
MoE model families the way the surrounding TPU training stack does. The
capacity/dispatch formulation follows the public Switch Transformer recipe
(Fedus et al., 2021, arXiv:2101.03961).

Use inside ``shard_map`` over a mesh with an expert axis::

    @partial(shard_map, mesh=mesh,
             in_specs=(P("ep"), P(), P("ep"), P("ep")), out_specs=P("ep"))
    def run(x, wg, w1, w2):
        return moe_apply(x, wg, w1[0], w2[0], axis_name="ep", capacity=C)
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax


def _route(
    x: jax.Array, wg: jax.Array
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Top-1 gating: per-token expert index, gate probability, and the
    token's arrival position within its expert's queue (source order)."""
    probs = jax.nn.softmax(x @ wg, axis=-1)
    expert = jnp.argmax(probs, axis=-1)
    gate = jnp.max(probs, axis=-1)
    onehot = jax.nn.one_hot(expert, wg.shape[-1], dtype=jnp.int32)
    position = jnp.sum((jnp.cumsum(onehot, axis=0) - 1) * onehot, axis=-1)
    return expert, gate, position


def moe_apply(
    x: jax.Array,
    wg: jax.Array,
    w1: jax.Array,
    w2: jax.Array,
    *,
    axis_name: str,
    capacity: int,
) -> jax.Array:
    """Dispatch local tokens through the device-sharded experts.

    Args:
        x: ``(n, d)`` this device's token shard.
        wg: ``(d, E)`` gate weights, replicated.
        w1: ``(d, h)`` this device's expert up-projection.
        w2: ``(h, d)`` this device's expert down-projection.
        axis_name: the expert mesh axis (E = its size).
        capacity: max tokens each (source device, expert) pair may send;
            overflow tokens get zero output.

    Returns the ``(n, d)`` combined output: ``gate * expert(x)`` per kept
    token, zero for dropped tokens.
    """
    num_experts = lax.psum(1, axis_name)
    n, d = x.shape
    expert, gate, position = _route(x, wg)
    keep = position < capacity

    # pack into (E, C+1, d); slot C is the spill row every dropped token
    # writes to (and is then cut off), so kept tokens never collide
    slot = jnp.where(keep, position, capacity)
    dispatch = jnp.zeros((num_experts, capacity + 1, d), x.dtype)
    dispatch = dispatch.at[expert, slot].set(x)[:, :capacity]

    # exchange: leading axis goes from "destination expert" to "source
    # device" — each device now holds every shard's tokens for ITS expert
    received = lax.all_to_all(dispatch, axis_name, 0, 0, tiled=True)

    hidden = jax.nn.relu(received.reshape(-1, d) @ w1)
    processed = (hidden @ w2).reshape(num_experts, capacity, d)

    # send results back and gather each token's row from its expert buffer
    returned = lax.all_to_all(processed, axis_name, 0, 0, tiled=True)
    padded = jnp.concatenate(
        [returned, jnp.zeros((num_experts, 1, d), returned.dtype)], axis=1
    )
    return padded[expert, slot] * gate[:, None]


def moe_reference(
    x: jax.Array,
    wg: jax.Array,
    w1: jax.Array,
    w2: jax.Array,
    *,
    num_shards: int,
    capacity: int,
) -> jax.Array:
    """Unsharded oracle with identical routing/drop semantics.

    ``x`` is the full ``(N, d)`` batch laid out as ``num_shards``
    contiguous shards; ``w1``/``w2`` carry the expert axis in front
    (``(E, d, h)`` / ``(E, h, d)``).
    """
    outs = []
    for shard in jnp.split(x, num_shards, axis=0):
        expert, gate, position = _route(shard, wg)
        keep = position < capacity
        y = jnp.einsum(
            "nh,nhd->nd",
            jax.nn.relu(jnp.einsum("nd,ndh->nh", shard, w1[expert])),
            w2[expert],
        )
        outs.append(jnp.where(keep[:, None], y * gate[:, None], 0.0))
    return jnp.concatenate(outs, axis=0)
