"""In-jit sharded sync tests: metric counters synced with lax.psum inside a
shard_map'd step over an 8-device mesh — the TPU-native fast path."""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:  # pre-0.4.38 jax keeps it under experimental
    from jax.experimental.shard_map import shard_map

from torcheval_tpu.metrics import MulticlassAccuracy, Max, Min
from torcheval_tpu.metrics.functional.classification.accuracy import (
    _multiclass_accuracy_update,
)
from torcheval_tpu.metrics.metric import MergeKind
from torcheval_tpu.metrics.sharded import (
    state_merge_specs,
    sync_states_in_jit,
    tree_add,
)

CPUS = jax.devices("cpu")


def _mesh(n=8):
    return Mesh(np.array(CPUS[:n]), ("dp",))


def test_psum_counter_sync_matches_eager_metric():
    mesh = _mesh()
    n_dev = 8
    rng = np.random.default_rng(11)
    x = rng.uniform(size=(n_dev * 16, 5)).astype(np.float32)
    y = rng.integers(0, 5, size=(n_dev * 16,))

    metric = MulticlassAccuracy()
    specs = state_merge_specs(metric)

    @jax.jit
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P("dp"), P("dp")),
        out_specs=P(),
    )
    def eval_step(xs, ys):
        num_correct, num_total = _multiclass_accuracy_update(xs, ys, "micro", None, 1)
        local = {"num_correct": num_correct, "num_total": num_total}
        return sync_states_in_jit(local, "dp", specs)

    xs = jax.device_put(jnp.asarray(x), NamedSharding(mesh, P("dp")))
    ys = jax.device_put(jnp.asarray(y), NamedSharding(mesh, P("dp")))
    synced = eval_step(xs, ys)

    # load the synced state back into the class metric for reporting
    metric.load_state_dict(synced)
    expected = np.mean(x.argmax(1) == y)
    np.testing.assert_allclose(np.asarray(metric.compute()), expected, rtol=1e-6)


def test_state_accumulation_across_steps():
    mesh = _mesh(4)
    rng = np.random.default_rng(5)
    specs = {"num_correct": MergeKind.SUM, "num_total": MergeKind.SUM}

    @jax.jit
    @partial(
        shard_map, mesh=mesh, in_specs=(P(), P("dp"), P("dp")), out_specs=P()
    )
    def step(state, xs, ys):
        nc, nt = _multiclass_accuracy_update(xs, ys, "micro", None, 1)
        local = sync_states_in_jit(
            {"num_correct": nc, "num_total": nt}, "dp", specs
        )
        return tree_add(state, local)

    state = {"num_correct": jnp.zeros(()), "num_total": jnp.zeros(())}
    total_correct = 0
    total = 0
    for _ in range(3):
        x = rng.uniform(size=(8, 3)).astype(np.float32)
        y = rng.integers(0, 3, size=(8,))
        total_correct += int(np.sum(x.argmax(1) == y))
        total += 8
        state = step(
            state,
            jax.device_put(jnp.asarray(x), NamedSharding(mesh, P("dp"))),
            jax.device_put(jnp.asarray(y), NamedSharding(mesh, P("dp"))),
        )
    np.testing.assert_allclose(float(state["num_correct"]), total_correct)
    np.testing.assert_allclose(float(state["num_total"]), total)


def test_pmax_pmin_and_extend():
    mesh = _mesh(4)
    specs = {
        "mx": MergeKind.MAX,
        "mn": MergeKind.MIN,
        "buf": MergeKind.EXTEND,
    }

    @jax.jit
    @partial(shard_map, mesh=mesh, in_specs=P("dp"), out_specs=P())
    def step(xs):
        local = {
            "mx": jnp.max(xs),
            "mn": jnp.min(xs),
            "buf": xs,
        }
        return sync_states_in_jit(local, "dp", specs)

    x = jnp.arange(16.0)
    out = step(jax.device_put(x, NamedSharding(mesh, P("dp"))))
    assert float(out["mx"]) == 15.0
    assert float(out["mn"]) == 0.0
    np.testing.assert_allclose(np.sort(np.asarray(out["buf"])), np.arange(16.0))


def test_custom_kind_raises():
    specs = {"s": MergeKind.CUSTOM}
    mesh = _mesh(2)
    import pytest

    @partial(shard_map, mesh=mesh, in_specs=P("dp"), out_specs=P())
    def step(xs):
        return sync_states_in_jit({"s": jnp.sum(xs)}, "dp", specs)

    with pytest.raises(NotImplementedError, match="custom merges"):
        step(jax.device_put(jnp.arange(4.0), NamedSharding(mesh, P("dp"))))


# ---------------------------------------------------------- composed axes


def _composed_mesh(shape=(4, 2)):
    n = shape[0] * shape[1]
    return Mesh(np.array(CPUS[:n]).reshape(shape), ("dp", "sp"))


def test_composed_axes_sum_max_min_extend_match_eager_oracle():
    """sync_states_in_jit over the axis TUPLE ("dp","sp") — the composed
    8-device mesh — must agree with the eager per-shard merge, and EXTEND
    gather order must follow the axes' row-major linear index so results
    are BIT-identical, not just set-equal (VERDICT r5 weak #2)."""
    mesh = _composed_mesh()
    n_shards, per = 8, 4
    rng = np.random.default_rng(3)
    x = rng.normal(size=(n_shards * per,)).astype(np.float32)
    specs = {
        "total": MergeKind.SUM,
        "mx": MergeKind.MAX,
        "mn": MergeKind.MIN,
        "buf": MergeKind.EXTEND,
    }

    @jax.jit
    @partial(
        shard_map, mesh=mesh, in_specs=P(("dp", "sp")), out_specs=P()
    )
    def step(xs):
        local = {
            "total": jnp.sum(xs),
            "mx": jnp.max(xs),
            "mn": jnp.min(xs),
            "buf": xs,
        }
        return sync_states_in_jit(local, ("dp", "sp"), specs)

    out = step(
        jax.device_put(jnp.asarray(x), NamedSharding(mesh, P(("dp", "sp"))))
    )
    # eager oracle: shards in row-major (dp, sp) order == the input order
    np.testing.assert_array_equal(np.asarray(out["buf"]), x)
    np.testing.assert_allclose(
        float(out["total"]), np.sum(x, dtype=np.float32), rtol=1e-6
    )
    assert float(out["mx"]) == x.max()
    assert float(out["mn"]) == x.min()


def test_composed_axes_metric_counters_match_eager_metric():
    """MulticlassAccuracy counters synced over ("dp","sp") equal the
    plain eager metric on the whole batch."""
    mesh = _composed_mesh()
    rng = np.random.default_rng(17)
    x = rng.uniform(size=(64, 5)).astype(np.float32)
    y = rng.integers(0, 5, size=(64,))
    metric = MulticlassAccuracy()
    specs = state_merge_specs(metric)

    @jax.jit
    @partial(
        shard_map, mesh=mesh,
        in_specs=(P(("dp", "sp")), P(("dp", "sp"))), out_specs=P(),
    )
    def eval_step(xs, ys):
        nc, nt = _multiclass_accuracy_update(xs, ys, "micro", None, 1)
        return sync_states_in_jit(
            {"num_correct": nc, "num_total": nt}, ("dp", "sp"), specs
        )

    synced = eval_step(
        jax.device_put(jnp.asarray(x), NamedSharding(mesh, P(("dp", "sp")))),
        jax.device_put(jnp.asarray(y), NamedSharding(mesh, P(("dp", "sp")))),
    )
    metric.load_state_dict(synced)
    np.testing.assert_allclose(
        np.asarray(metric.compute()), np.mean(x.argmax(1) == y), rtol=1e-6
    )


# --------------------------------------------------------- payload trimming


def test_extend_valid_trims_gather_to_bucket():
    """extend_valid slices an over-provisioned buffer to the smallest
    power-of-2 bucket covering the bound before the gather: the gathered
    result carries each shard's bucket prefix (valid rows + neutral fill),
    in shard order."""
    mesh = _mesh(4)
    capacity, valid = 64, 5  # bucket(5) = 8
    specs = {"buf": MergeKind.EXTEND}
    fill = -np.inf
    shards = []
    for r in range(4):
        buf = np.full((capacity,), fill, np.float32)
        buf[:valid] = np.arange(valid) + 10 * r
        shards.append(buf)
    x = np.concatenate(shards)

    @jax.jit
    @partial(shard_map, mesh=mesh, in_specs=P("dp"), out_specs=P())
    def step(xs):
        return sync_states_in_jit(
            {"buf": xs}, "dp", specs, extend_valid={"buf": valid}
        )

    out = step(jax.device_put(jnp.asarray(x), NamedSharding(mesh, P("dp"))))
    assert out["buf"].shape == (4 * 8,)  # bucket(5) = 8 per shard, not 64
    got = np.asarray(out["buf"]).reshape(4, 8)
    for r in range(4):
        np.testing.assert_array_equal(got[r, :valid], shards[r][:valid])
        assert np.all(np.isneginf(got[r, valid:]))  # neutral fill intact


def test_extend_bf16_compression_opt_in():
    """config.sync_compression("bf16") halves the EXTEND wire dtype; the
    gathered result is cast back and equals the bf16-rounded input. Off by
    default: exact bytes."""
    from torcheval_tpu import config as te_config

    mesh = _mesh(4)
    rng = np.random.default_rng(5)
    x = rng.normal(size=(4 * 512,)).astype(np.float32)
    specs = {"buf": MergeKind.EXTEND}

    def build():
        @jax.jit
        @partial(shard_map, mesh=mesh, in_specs=P("dp"), out_specs=P())
        def step(xs):
            return sync_states_in_jit({"buf": xs}, "dp", specs)

        return step

    exact = build()(
        jax.device_put(jnp.asarray(x), NamedSharding(mesh, P("dp")))
    )
    np.testing.assert_array_equal(np.asarray(exact["buf"]), x)

    with te_config.sync_compression_mode("bf16"):
        lossy = build()(
            jax.device_put(jnp.asarray(x), NamedSharding(mesh, P("dp")))
        )
    assert lossy["buf"].dtype == jnp.float32  # cast back after the wire
    np.testing.assert_array_equal(
        np.asarray(lossy["buf"]), x.astype(jnp.bfloat16).astype(np.float32)
    )


def test_extend_int8_compression_within_codec_bound():
    """compression="int8" quantizes the EXTEND gather INSIDE the jitted
    program (one uint8 all-gather replaces the float one): every shard's
    gathered values land within the codec's published hard bound, while
    the integer counter synced alongside stays bit-exact."""
    from torcheval_tpu import config as te_config
    from torcheval_tpu import wire

    mesh = _mesh(4)
    rng = np.random.default_rng(7)
    shards = [
        (rng.normal(size=512) * 3.0).astype(np.float32) for _ in range(4)
    ]
    x = np.concatenate(shards)
    n = np.arange(1, 5, dtype=np.int32)
    specs = {"buf": MergeKind.EXTEND, "n": MergeKind.SUM}

    @jax.jit
    @partial(
        shard_map, mesh=mesh, in_specs=(P("dp"), P("dp")), out_specs=P()
    )
    def step(xs, ns):
        return sync_states_in_jit(
            {"buf": xs, "n": ns[0]}, "dp", specs, compression="int8"
        )

    out = step(
        jax.device_put(jnp.asarray(x), NamedSharding(mesh, P("dp"))),
        jax.device_put(jnp.asarray(n), NamedSharding(mesh, P("dp"))),
    )
    assert out["buf"].dtype == jnp.float32  # dequantized after the wire
    assert int(out["n"]) == int(n.sum())  # integer counter untouched
    got = np.asarray(out["buf"]).reshape(4, 512)
    block = te_config.wire_block_size()
    for r in range(4):
        bound = wire.int8_error_bound(shards[r], block)
        assert float(np.max(np.abs(got[r] - shards[r]))) <= bound
        assert bound < 0.04  # the bound itself is meaningfully tight


def test_shard_spec_int8_reduce_scatter_matches_oracle_within_bound():
    """Owner-partitioned SUM at the int8 rung: the quantized all_to_all
    exchange lands each owner's block within the COMPOUNDED bound (one
    codec error per contributing rank), and the result stays sharded."""
    from torcheval_tpu import config as te_config
    from torcheval_tpu import wire
    from torcheval_tpu.metrics import ShardSpec

    mesh = _mesh(4)
    rng = np.random.default_rng(8)
    deltas = rng.normal(size=(4, 1024)).astype(np.float32)

    @jax.jit
    @partial(shard_map, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))
    def step(d):
        out = sync_states_in_jit(
            {"hist": d[0]},
            "dp",
            {"hist": MergeKind.SUM},
            compression="int8",
            shard_specs={"hist": ShardSpec(axis=0)},
        )
        return out["hist"]

    owned = step(
        jax.device_put(jnp.asarray(deltas), NamedSharding(mesh, P("dp")))
    )
    assert owned.shape == (1024,)
    assert not owned.sharding.is_fully_replicated  # stays partitioned
    oracle = deltas.astype(np.float64).sum(axis=0)
    block = te_config.wire_block_size()
    bound = sum(wire.int8_error_bound(deltas[r], block) for r in range(4))
    assert float(np.max(np.abs(np.asarray(owned) - oracle))) <= bound
