// Fused cross-entropy NLL — C++ XLA custom-call (CPU host kernel).
//
// Per-row streaming logsumexp + target gather for Perplexity's update
// (torcheval_tpu/metrics/functional/text/perplexity.py). The pure-XLA path
// is the fused log_softmax kernel in that module; on the CPU backend XLA
// lowers exp through scalar libm, which is ~4x slower than SIMD — this
// kernel restores vector width with a branch-free polynomial exp2 that the
// autovectorizer can lift (compiled -Ofast -march=native, see
// native/__init__.py). Parity role: the reference leans on torch's fused
// vectorized cross_entropy CPU kernel (reference
// torcheval/metrics/functional/text/perplexity.py:66-107).
//
// Inputs:  logits (R, V) f32, targets (R,) s32.
// Attrs:   ignore_index s64, has_ignore s64 (0/1).
// Outputs: nll () f32 — sum over kept rows of logsumexp(row) - row[target],
//          count () s32 — number of kept rows.

#include <cmath>
#include <cstdint>

#include "xla/ffi/api/ffi.h"

namespace ffi = xla::ffi;

namespace {

// exp(x) for x <= 0 (inputs are pre-shifted by the row max), accurate to
// ~2e-7 relative: 2^t split into integer/fraction parts, 2^f by a degree-6
// Taylor-in-ln2 polynomial, 2^i via exponent bits. No libm in the loop body
// so the autovectorizer keeps full SIMD width.
inline float ExpNeg(float x) {
  float t = x * 1.44269504088896341f;  // log2(e)
  t = t < -126.0f ? -126.0f : t;
  float fi = __builtin_floorf(t);
  float f = t - fi;
  float p = 1.53775046548083101e-4f;
  p = p * f + 1.33990589483162226e-3f;
  p = p * f + 9.61817794372693013e-3f;
  p = p * f + 5.55041086648215500e-2f;
  p = p * f + 2.40226506959100712e-1f;
  p = p * f + 6.93147180559945286e-1f;
  p = p * f + 1.0f;
  union {
    uint32_t u;
    float fl;
  } scale;
  scale.u = static_cast<uint32_t>(static_cast<int32_t>(fi) + 127) << 23;
  return p * scale.fl;
}

// Kept free of everything but the two loops so both stay vectorizable (the
// autovectorizer refuses loop nests wrapped in extra control flow — even
// the target-index clamp in this function's body regresses the exp loop to
// scalar). noinline: inlining into the stateful caller loop has the same
// effect.
__attribute__((noinline)) float RowLse(const float* row, int64_t vocab) {
  float m = row[0];
  for (int64_t v = 1; v < vocab; ++v) m = row[v] > m ? row[v] : m;
  float s = 0.0f;
  for (int64_t v = 0; v < vocab; ++v) s += ExpNeg(row[v] - m);
  return std::log(s) + m;
}

// Out-of-range targets follow the pure-XLA path's
// take_along_axis(mode="clip") semantics: negative indices wrap from the
// end once, then everything clamps into [0, vocab-1].
inline int64_t ClipIndex(int32_t t, int64_t vocab) {
  int64_t tc = t < 0 ? t + vocab : t;
  return tc < 0 ? 0 : (tc >= vocab ? vocab - 1 : tc);
}

// Non-finite detection in the integer domain: -ffast-math lets the
// compiler fold float isnan checks and the vectorized max/clamp blends
// drop NaN operands, so the IEEE bit patterns are the only reliable
// signal. Sets ``bad`` when the row contains NaN or +Inf (logsumexp is NaN
// either way, matching XLA's max-propagates-NaN / Inf-Inf semantics) and
// ``all_neg_inf`` when every element is -Inf (XLA: empty softmax -> NaN).
// A row with some -Inf but a finite max stays on the fast path — those
// elements contribute exp(-Inf)=0 exactly like XLA.
__attribute__((noinline)) void RowScan(const float* row, int64_t vocab,
                                       uint32_t* bad,
                                       uint32_t* all_neg_inf) {
  uint32_t any_bad = 0;
  uint32_t all_ninf = 1;
  for (int64_t v = 0; v < vocab; ++v) {
    uint32_t b;
    __builtin_memcpy(&b, row + v, sizeof(b));
    const uint32_t mag = b & 0x7FFFFFFFu;
    any_bad |= static_cast<uint32_t>((mag > 0x7F800000u) |
                                     (b == 0x7F800000u));
    all_ninf &= static_cast<uint32_t>(b == 0xFF800000u);
  }
  *bad = any_bad;
  *all_neg_inf = all_ninf;
}

}  // namespace

static ffi::Error CrossEntropyNllImpl(ffi::Buffer<ffi::F32> logits,
                                      ffi::Buffer<ffi::S32> targets,
                                      int64_t ignore_index, int64_t has_ignore,
                                      ffi::ResultBuffer<ffi::F32> nll,
                                      ffi::ResultBuffer<ffi::S32> count) {
  const auto dims = logits.dimensions();
  if (dims.size() != 2) {
    return ffi::Error::InvalidArgument("logits must be rank 2 (rows, vocab)");
  }
  const int64_t rows = dims[0];
  const int64_t vocab = dims[1];
  const auto tdims = targets.dimensions();
  if (tdims.size() != 1 || tdims[0] != rows) {
    return ffi::Error::InvalidArgument("targets must be (rows,)");
  }

  const float* x = logits.typed_data();
  const int32_t* tg = targets.typed_data();

  double total = 0.0;
  int64_t kept = 0;
  for (int64_t r = 0; r < rows; ++r) {
    const int32_t t = tg[r];
    if (has_ignore && t == ignore_index) continue;
    const float* row = x + r * vocab;
    ++kept;
    uint32_t bad, all_neg_inf;
    RowScan(row, vocab, &bad, &all_neg_inf);
    if (bad | all_neg_inf) {
      total += static_cast<double>(__builtin_nanf(""));
      continue;
    }
    total += static_cast<double>(RowLse(row, vocab)) -
             static_cast<double>(row[ClipIndex(t, vocab)]);
  }
  nll->typed_data()[0] = static_cast<float>(total);
  count->typed_data()[0] = static_cast<int32_t>(kept);
  return ffi::Error::Success();
}

XLA_FFI_DEFINE_HANDLER_SYMBOL(CrossEntropyNll, CrossEntropyNllImpl,
                              ffi::Ffi::Bind()
                                  .Arg<ffi::Buffer<ffi::F32>>()
                                  .Arg<ffi::Buffer<ffi::S32>>()
                                  .Attr<int64_t>("ignore_index")
                                  .Attr<int64_t>("has_ignore")
                                  .Ret<ffi::Buffer<ffi::F32>>()
                                  .Ret<ffi::Buffer<ffi::S32>>());
