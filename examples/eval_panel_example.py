"""A realistic eval panel: K metrics, one dispatch per batch, checkpointed.

The pattern most eval loops want (reference examples call each metric's
``update`` separately; here the whole panel fuses):

- ``toolkit.update_collection`` traces every fusable metric into ONE XLA
  program per batch — counters, confusion matrix, windowed ring, and the
  streaming AUROC histogram together;
- ``sync_and_compute_collection`` values the panel mid-stream (world of
  one here; the same call syncs replicas on a mesh or pod);
- ``save_metric_state``/``load_metric_state`` round-trip the panel through
  an Orbax checkpoint, resuming accumulation exactly where it stopped.
"""

import os

import sys as _sys

# file-relative fallback: `python -m examples.<name>` resolves imports from
# the CWD, not this directory, so `_backend` needs the examples dir on
# sys.path (direct `python examples/<name>.py` runs already have it)
_here = os.path.dirname(os.path.abspath(__file__))
_sys.path.append(_here)
_sys.path.append(os.path.dirname(_here))  # repo root: uninstalled checkouts

from _backend import ensure_backend

ensure_backend()  # fall back to CPU if the accelerator relay is unreachable

import tempfile

import jax
import jax.numpy as jnp
import numpy as np

import torcheval_tpu.metrics as M
from torcheval_tpu.metrics.toolkit import (
    sync_and_compute_collection,
    update_collection,
)
from torcheval_tpu.utils import load_metric_state, save_metric_state

CLASSES, BATCH, STEPS = 10, 256, 12


def main() -> None:
    rng = np.random.default_rng(0)

    panel = {
        "accuracy": M.MulticlassAccuracy(),
        "f1_macro": M.MulticlassF1Score(
            num_classes=CLASSES, average="macro"
        ),
        "confusion": M.MulticlassConfusionMatrix(CLASSES),
        "win_acc": M.WindowedClickThroughRate(max_num_updates=4),
        "confidence_auroc": M.StreamingBinaryAUROC(),
    }

    for step in range(1, STEPS + 1):
        # a model would produce these; the panel only sees (logits, labels)
        logits = jnp.asarray(
            rng.normal(size=(BATCH, CLASSES)).astype(np.float32)
        )
        labels = jnp.asarray(rng.integers(0, CLASSES, size=(BATCH,)))

        # the multiclass metrics fuse into one program on the raw batch
        update_collection(
            {k: panel[k] for k in ("accuracy", "f1_macro", "confusion")},
            logits,
            labels,
        )
        # derived streams: was-the-argmax-right as a windowed rate, and
        # predicted-class confidence scored against correctness (a
        # calibration-flavored AUROC over the model's own certainty)
        probs = jax.nn.softmax(logits, axis=-1)
        correct = (jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32)
        confidence = jnp.max(probs, axis=-1)
        panel["win_acc"].update(correct)
        panel["confidence_auroc"].update(confidence, correct)

        if step % 4 == 0:
            values = sync_and_compute_collection(panel)
            lifetime_acc = float(values["accuracy"])
            # windowed metrics return (lifetime, windowed), (num_tasks,) each
            windowed = float(np.asarray(values["win_acc"][1])[0])
            print(
                f"step {step:2d}: acc={lifetime_acc:.3f} "
                f"f1={float(values['f1_macro']):.3f} "
                f"win_acc={windowed:.3f} "
                f"conf_auroc={float(values['confidence_auroc']):.3f}"
            )

    with tempfile.TemporaryDirectory() as ckpt_dir:
        path = os.path.join(ckpt_dir, "panel")
        save_metric_state(panel, path)
        restored = {
            "accuracy": M.MulticlassAccuracy(),
            "f1_macro": M.MulticlassF1Score(
                num_classes=CLASSES, average="macro"
            ),
            "confusion": M.MulticlassConfusionMatrix(CLASSES),
            "win_acc": M.WindowedClickThroughRate(max_num_updates=4),
            "confidence_auroc": M.StreamingBinaryAUROC(),
        }
        load_metric_state(restored, path)
        before = float(panel["accuracy"].compute())
        after = float(restored["accuracy"].compute())
        assert abs(before - after) < 1e-7, (before, after)
        print(f"checkpoint round-trip ok: accuracy {after:.3f}")

    cm = np.asarray(panel["confusion"].compute())
    print(f"confusion matrix trace fraction: {np.trace(cm) / cm.sum():.3f}")
    print("eval panel done")


if __name__ == "__main__":
    main()
