"""FID pipeline parity checks (reference torcheval/metrics/image/fid.py:28-50).

Three layers, by what this image can run:

1. resize parity: ``jax.image.resize(..., antialias=False)`` vs the
   reference's ``F.interpolate(mode='bilinear', align_corners=False)`` —
   torch is available, so this runs everywhere.
2. transform_input: the torchvision channelwise affine applied by
   ``inception_v3(weights='DEFAULT')`` (ADVICE round-1 high finding) —
   verified against a hand-computed transform.
3. pooled-feature parity with real torchvision weights — skipped unless
   torchvision is installed (not in this image); runs in CI with weights.
"""

from __future__ import annotations

import numpy as np
import pytest
import jax
import jax.numpy as jnp

RNG = np.random.default_rng(3)

try:
    import torch
    import torch.nn.functional as F

    HAVE_TORCH = True
except Exception:
    HAVE_TORCH = False

try:
    from torchvision import models as _tv_models

    # ref_oracle.py stubs torchvision into sys.modules for reference-oracle
    # imports; require the real API, not the stub
    HAVE_TORCHVISION = hasattr(_tv_models, "inception_v3")
except Exception:
    HAVE_TORCHVISION = False


@pytest.mark.skipif(not HAVE_TORCH, reason="torch unavailable")
@pytest.mark.parametrize("hw", [(64, 64), (512, 640)])  # up- and downscale
def test_resize_matches_reference_interpolate(hw):
    h, w = hw
    img = RNG.uniform(size=(2, 3, h, w)).astype(np.float32)

    ref = F.interpolate(
        torch.tensor(img), size=(299, 299), mode="bilinear",
        align_corners=False,
    ).numpy()

    x = jnp.transpose(jnp.asarray(img), (0, 2, 3, 1))
    ours = jax.image.resize(
        x, (2, 299, 299, 3), method="bilinear", antialias=False
    )
    ours = np.transpose(np.asarray(ours), (0, 3, 1, 2))
    np.testing.assert_allclose(ours, ref, atol=2e-5)


@pytest.mark.slow
def test_transform_input_affine():
    """InceptionV3.transform_input applies torchvision's channelwise remap
    of [0,1] pixels to the ImageNet scale the pretrained weights expect."""
    from torcheval_tpu.models.inception import InceptionV3

    x = jnp.asarray(RNG.uniform(size=(1, 299, 299, 3)).astype(np.float32))

    with_t = InceptionV3(transform_input=True)
    without_t = InceptionV3(transform_input=False)
    params = with_t.init(jax.random.PRNGKey(0), x)

    manual = jnp.concatenate(
        [
            x[..., 0:1] * (0.229 / 0.5) + (0.485 - 0.5) / 0.5,
            x[..., 1:2] * (0.224 / 0.5) + (0.456 - 0.5) / 0.5,
            x[..., 2:3] * (0.225 / 0.5) + (0.406 - 0.5) / 0.5,
        ],
        axis=-1,
    )
    a = with_t.apply(params, x)
    b = without_t.apply(params, manual)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


@pytest.mark.skipif(
    not HAVE_TORCHVISION, reason="torchvision (pretrained weights) unavailable"
)
def test_pooled_features_match_torchvision():
    """End-to-end: imported weights + [0,1] images -> pooled 2048-d features
    within tolerance of the torch model (reference fid.py:28-50)."""
    from torcheval_tpu.metrics.image.fid import FIDInceptionV3
    from torchvision import models

    imgs = RNG.uniform(size=(4, 3, 299, 299)).astype(np.float32)

    torch_model = models.inception_v3(weights="DEFAULT")
    torch_model.fc = torch.nn.Identity()
    torch_model.eval()
    with torch.no_grad():
        ref_feats = torch_model(torch.tensor(imgs)).numpy()

    ours = FIDInceptionV3()(jnp.asarray(imgs))
    np.testing.assert_allclose(np.asarray(ours), ref_feats, atol=1e-3)
