// Row-wise argmax — C++ XLA custom-call (CPU host kernel).
//
// One fused pass replacing the CPU lowering of `argmax_last`
// (torcheval_tpu/metrics/functional/tensor_utils.py): the XLA formulation
// must materialize an order-preserving integer key array plus two reduces
// (max, then first-matching-index), ~3 passes over the batch; this kernel
// streams each row once tracking (best_key, first_index). Feeds every
// score->label conversion in the classification hot loops (accuracy,
// precision, recall, F1, confusion matrix).
//
// Semantics pinned to jnp.argmax(axis=-1): FIRST index on ties, NaN of
// either sign ranks maximal, -0.0 ties with +0.0. Subnormals keep their
// exact IEEE order (the bitcast key preserves them; only the sort kernel
// needed XLA's flush-to-zero tie class).
//
// Inputs:  scores (R, C) f32.
// Outputs: index (R,) s32.

#include <cstdint>
#include <cstring>
#include <vector>

#include "xla/ffi/api/ffi.h"

namespace ffi = xla::ffi;

namespace {

// Ascending unsigned key == ascending float order (IEEE total-order map),
// with +-0 collapsed and NaN forced maximal. Branchless so the max
// reduction below vectorizes to integer-max blends.
inline uint32_t AscKey(float x) {
  uint32_t b;
  std::memcpy(&b, &x, sizeof(b));
  const uint32_t mag = b & 0x7FFFFFFFu;
  // all-ones masks instead of ?: — ternaries lower to branches that stop
  // the caller's reduction loop from vectorizing
  const uint32_t sign = static_cast<uint32_t>(static_cast<int32_t>(b) >> 31);
  uint32_t k = (b ^ sign) | (~sign & 0x80000000u);
  const uint32_t zero = static_cast<uint32_t>(
      -static_cast<int32_t>(mag == 0u));  // -0.0 ties with +0.0
  k = (k & ~zero) | (0x80000000u & zero);
  const uint32_t nan = static_cast<uint32_t>(
      -static_cast<int32_t>(mag > 0x7F800000u));  // NaN ranks maximal
  return k | nan;
}

// A loop-carried argmax (value + index together) defeats the
// autovectorizer, so split into three vectorizable passes over the row
// (which lives in L1): keys into scratch, unsigned-max reduce, then a
// min-reduce over matching indices (first max = smallest match).
__attribute__((noinline)) void RowKeys(const float* row, int64_t c,
                                       uint32_t* keys) {
  for (int64_t i = 0; i < c; ++i) keys[i] = AscKey(row[i]);
}

__attribute__((noinline)) uint32_t MaxKey(const uint32_t* keys, int64_t c) {
  uint32_t m = 0;
  for (int64_t i = 0; i < c; ++i) m = keys[i] > m ? keys[i] : m;
  return m;
}

__attribute__((noinline)) int32_t FirstMatch(const uint32_t* keys, int64_t c,
                                             uint32_t m) {
  int32_t mn = INT32_MAX;
  for (int64_t i = 0; i < c; ++i) {
    const int32_t v = keys[i] == m ? static_cast<int32_t>(i) : INT32_MAX;
    mn = v < mn ? v : mn;
  }
  return mn;
}

int32_t RowArgmax(const float* row, int64_t c, uint32_t* scratch) {
  RowKeys(row, c, scratch);
  return FirstMatch(scratch, c, MaxKey(scratch, c));
}

// Count of positions beating the target under argmax's tie rule: any
// strictly-greater key, or an equal key at a smaller index. Zero
// violations == argmax(row) == t. One branchless vectorizable pass —
// unlike full argmax there is no per-row index bookkeeping, so short rows
// (C ~ 100) don't drown in reduction prologues.
__attribute__((noinline)) int64_t RowViolations(const float* row, int64_t c,
                                                uint32_t kt, int64_t t) {
  int64_t n = 0;
  for (int64_t j = 0; j < c; ++j) {
    const uint32_t k = AscKey(row[j]);
    n += static_cast<int64_t>((k > kt) | ((k == kt) & (j < t)));
  }
  return n;
}

}  // namespace

static ffi::Error CorrectMaskImpl(ffi::Buffer<ffi::F32> scores,
                                  ffi::Buffer<ffi::S32> targets,
                                  ffi::ResultBuffer<ffi::F32> mask) {
  const auto dims = scores.dimensions();
  if (dims.size() != 2) {
    return ffi::Error::InvalidArgument("scores must be rank 2 (rows, c)");
  }
  const int64_t rows = dims[0];
  const int64_t c = dims[1];
  const auto tdims = targets.dimensions();
  if (tdims.size() != 1 || tdims[0] != rows) {
    return ffi::Error::InvalidArgument("targets must be (rows,)");
  }
  const float* x = scores.typed_data();
  const int32_t* tg = targets.typed_data();
  float* out = mask->typed_data();
  for (int64_t r = 0; r < rows; ++r) {
    const int64_t t = tg[r];
    if (t < 0 || t >= c) {  // out-of-range target can never match argmax
      out[r] = 0.0f;
      continue;
    }
    const float* row = x + r * c;
    out[r] =
        RowViolations(row, c, AscKey(row[t]), t) == 0 ? 1.0f : 0.0f;
  }
  return ffi::Error::Success();
}

XLA_FFI_DEFINE_HANDLER_SYMBOL(CorrectMask, CorrectMaskImpl,
                              ffi::Ffi::Bind()
                                  .Arg<ffi::Buffer<ffi::F32>>()
                                  .Arg<ffi::Buffer<ffi::S32>>()
                                  .Ret<ffi::Buffer<ffi::F32>>());

static ffi::Error ArgmaxLastImpl(ffi::Buffer<ffi::F32> scores,
                                 ffi::ResultBuffer<ffi::S32> index) {
  const auto dims = scores.dimensions();
  if (dims.size() != 2) {
    return ffi::Error::InvalidArgument("scores must be rank 2 (rows, c)");
  }
  const int64_t rows = dims[0];
  const int64_t c = dims[1];
  if (c == 0) {
    return ffi::Error::InvalidArgument("argmax over an empty axis");
  }
  const float* x = scores.typed_data();
  int32_t* out = index->typed_data();
  std::vector<uint32_t> scratch(c);
  for (int64_t r = 0; r < rows; ++r) {
    out[r] = RowArgmax(x + r * c, c, scratch.data());
  }
  return ffi::Error::Success();
}

XLA_FFI_DEFINE_HANDLER_SYMBOL(ArgmaxLast, ArgmaxLastImpl,
                              ffi::Ffi::Bind()
                                  .Arg<ffi::Buffer<ffi::F32>>()
                                  .Ret<ffi::Buffer<ffi::S32>>());
