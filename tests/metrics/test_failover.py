"""Rank-loss autopilot (ISSUE 19): coordinated detect → reconstruct →
reform → live rejoin across the serving stack.

The acceptance criteria pinned here:

- a kill landing exactly on a committed generation boundary recovers
  with an EXACT (zero-loss) :class:`~torcheval_tpu.failover.LossBound`
  and the survivor world serves values BIT-IDENTICAL to the fault-free
  oracle; the revived rank then rejoins LIVE (no process restart) and
  every rank converges bit-identically again
  (``test_boundary_exact_recovery_and_live_rejoin``);
- a kill with undrained victim ingest declares a typed non-exact bound
  (``steps > 0``) and the survivors converge to the adjusted oracle —
  all contributions minus exactly the victim's unrecoverable updates
  (``test_nonboundary_kill_declares_typed_loss_bound``);
- a drain BETWEEN the committed generation and the kill must not
  double-count the dead shard's already-delivered outbox entries — the
  epoch-lag strip (``test_drain_after_snapshot_strips_dead_outbox``);
- the full crash matrix: every :data:`KILL_POINTS` point × {sync,
  async} snapshot writer recovers, serves coherent observability on the
  REFORMED group, and round-trips an elastic snapshot at the rejoined
  full world (``test_kill_point_crash_matrix``);
- a ThreadWorld-8 two-region soak (federation + sync plane + overload
  traffic + link-delay chaos) killing the region LEADER mid-exchange:
  leadership fails over to the lowest surviving region rank, zero
  full-world collectives are issued by detection/recovery, admission
  outbox budgets rescale with the world, and the post-rejoin values are
  bit-identical to the fault-free oracle (``test_soak_*``).

Float bit-identity note: the ``ctr`` family data here is integer-valued
(clicks 0/1, weights 1.0), so every float sum is exact at any merge
order — survivor-subgroup folds, reformed-world drains and full-world
drains all produce identical bits (the PR 13 dyadic discipline).
"""

from __future__ import annotations

import threading

import numpy as np
import pytest

from torcheval_tpu import config
from torcheval_tpu import metrics as M
from torcheval_tpu import obs
from torcheval_tpu.elastic import ElasticSession
from torcheval_tpu.failover import FailureDomain, LossBound, current_domain
from torcheval_tpu.federation import Federation, InProcessLinkBus
from torcheval_tpu.metrics import ShardContext
from torcheval_tpu.metrics.toolkit import adopt_synced, sync_and_compute
from torcheval_tpu.resilience import ResilientGroup
from torcheval_tpu.syncplane import SyncPlane
from torcheval_tpu.table import MetricTable, tightest_staleness_budget
from torcheval_tpu.table._hash import hash_keys, owner_of
from torcheval_tpu.utils.test_utils import (
    KILL_POINTS,
    ChaosLinkTransport,
    InjectedCrash,
    KillGroup,
    KillSchedule,
    KillSpec,
    OverloadSchedule,
    ThreadWorld,
)

WORLD = 4
VICTIM = 2

REGIONS_2X2 = [("us", (0, 1)), ("eu", (2, 3))]
REGIONS_4X2 = [("us", (0, 1, 2, 3)), ("eu", (4, 5, 6, 7))]


@pytest.fixture(autouse=True)
def _failover_cleanup():
    yield
    import torcheval_tpu.failover as fo
    from torcheval_tpu.obs.counters import default_registry

    with fo._CURRENT_LOCK:
        fo._CURRENT = None
    default_registry().unregister("resilience")


@pytest.fixture
def rec():
    r = obs.recorder()
    prev = r.enabled
    r.reset()
    r.enable()
    try:
        yield r
    finally:
        r.reset()
        if not prev:
            r.disable()


def _batch(step, rank, pool=None, n=16):
    """Integer-valued ctr traffic (exact sums at any fold order)."""
    rng = np.random.default_rng(1000 + 17 * step + rank)
    if pool is None:
        keys = rng.integers(0, 60, n)
    else:
        keys = np.asarray(pool)[rng.integers(0, len(pool), n)]
    clicks = rng.integers(0, 2, n).astype(np.float32)
    return keys, clicks, np.ones(n, np.float32)


def _fault_free(world, steps, drains, *, skip=None, pool=None):
    """The uninterrupted oracle: every rank ingests every step (except
    ``skip[rank]`` and later, modeling the victim's lost updates), then
    ``drains`` adopt drains and one non-mutating global sync."""

    def body(g):
        t = MetricTable("ctr", shard=ShardContext(g.rank, world))
        for step in range(steps):
            if skip and g.rank in skip and step >= skip[g.rank]:
                continue
            t.ingest(*_batch(step, g.rank, pool=pool))
        for _ in range(drains):
            adopt_synced(t, g)
        return sync_and_compute(t, g).as_dict()

    return ThreadWorld(world).run(body)[0]


def _assert_same(vals, want, where=""):
    assert set(vals) == set(want), (where, len(vals), len(want))
    bad = {k: (vals[k], want[k]) for k in want if vals[k] != want[k]}
    assert not bad, (where, list(bad.items())[:5])


# ---------------------------------------------------------------------------
# The full recovery epoch: detect → reconstruct → reform → live rejoin
# ---------------------------------------------------------------------------


def test_boundary_exact_recovery_and_live_rejoin(tmp_path, rec):
    """Kill on a committed generation boundary: zero loss, survivor
    values bit-identical to the fault-free oracle, live rejoin converges
    every rank back to the oracle — plus the typed FailoverEvent ladder,
    state transitions and the degraded-world /healthz contract."""
    from torcheval_tpu.obs.server import healthz_payload

    want = _fault_free(WORLD, 4, 3)
    schedule = KillSchedule(
        [KillSpec("drain-commit", at=1, rank=VICTIM)], world=WORLD
    )
    rejoin_barrier = threading.Barrier(WORLD)
    results, health_snap = {}, {}

    def body(g):
        kg = KillGroup(g, schedule)
        rg = ResilientGroup(kg, timeout=20.0, retries=0, policy="quorum")
        t = MetricTable("ctr", shard=ShardContext(g.rank, WORLD))
        sess = ElasticSession(
            {"t": t}, str(tmp_path), process_group=rg, interval=10**9,
            fault_hook=schedule.fault_hook,
        )
        domain = FailureDomain({"t": t}, rg, session=sess, detect_after=2)
        assert domain.state == "armed" and domain.poll() == ()
        assert current_domain() is not None
        try:
            for step in range(4):
                t.ingest(*_batch(step, g.rank))
            schedule.check("drain-commit", g.rank)  # visit 0: all live
            domain.drain()
            sess.snapshot()
            schedule.check("drain-commit", g.rank)  # visit 1: victim dies
            # --- survivors only past this line ---
            for _ in range(2):
                sync_and_compute(t, rg)  # quorum syncs feed the streak
            dead = domain.poll()
            assert dead == (VICTIM,), dead
            assert domain.state == "degraded"
            loss = domain.recover()
            assert loss.exact and loss.steps == 0 and loss.epochs == 0
            assert loss.generation == 0 and loss.ranks == (VICTIM,)
            assert domain.state == "recovered"
            assert domain.survivors == (0, 1, 3)
            synced = domain.drain()
            _assert_same(
                synced["t"].compute().as_dict(), want, "survivor-world"
            )
            # the declared bound rides every synced metric's provenance
            prov = synced["t"].sync_provenance
            assert prov is not None and prov.loss == loss
            if g.rank == 0:
                import torcheval_tpu.failover as fo

                with fo._CURRENT_LOCK:
                    fo._CURRENT = domain
                health_snap[0] = healthz_payload()
                schedule.revive(VICTIM)
        except InjectedCrash:
            # the victim parks until revival, then rejoins LIVE: it
            # passes the dead set it was told and adopts the survivors'
            # declared loss alongside their carried state
            schedule.revival.wait(30.0)
            rejoin_barrier.wait(30.0)
            domain.rejoin(dead_ranks=(VICTIM,))
            assert domain.loss is not None and domain.loss.exact
            results[g.rank] = domain.drain()["t"].compute().as_dict()
            domain.close()
            return
        rejoin_barrier.wait(30.0)
        domain.rejoin()
        assert domain.state == "armed"
        assert domain.survivors == tuple(range(WORLD))
        results[g.rank] = domain.drain()["t"].compute().as_dict()
        domain.close()

    ThreadWorld(WORLD).run(body)

    assert sorted(results) == list(range(WORLD))
    for rank, vals in results.items():
        _assert_same(vals, want, f"post-rejoin rank {rank}")

    # /healthz while recovered-but-not-rejoined: graceful, non-failing
    payload = health_snap[0]
    assert payload["status"] == "degraded-world"
    assert payload["healthy"] is True
    assert payload["failover"]["state"] == "recovered"
    assert payload["failover"]["dead_ranks"] == [VICTIM]
    assert payload["failover"]["survivors"] == [0, 1, 3]
    assert payload["failover"]["loss"]["exact"] is True
    assert "reformed_to" in payload["sync"]
    assert "consecutive_missing" in payload["sync"]

    # the typed event ladder, in phase order per surviving rank
    from torcheval_tpu.obs.events import FailoverEvent, event_from_dict

    events = [e for e in rec.log.tail(None) if e.kind == "failover"]
    by_rank = {
        r: [e.action for e in events if e.rank == r] for r in range(WORLD)
    }
    for r in (0, 1, 3):
        assert by_rank[r] == [
            "detected", "reconstructed", "reformed", "rejoined"
        ], (r, by_rank[r])
    assert by_rank[VICTIM] == ["rejoined"]
    detected = next(e for e in events if e.action == "detected")
    assert detected.dead_ranks == (VICTIM,)
    rebuilt = next(e for e in events if e.action == "reconstructed")
    assert rebuilt.generation == 0 and rebuilt.loss_steps == 0
    # round-trip through the wire dict form
    clone = event_from_dict(events[0].as_dict())
    assert isinstance(clone, FailoverEvent)
    assert clone.action == events[0].action


def test_nonboundary_kill_declares_typed_loss_bound(tmp_path):
    """Victim ingested two steps after the committed generation without
    a drain: recovery declares ``steps == 2`` (epochs 0, not exact) and
    the survivors converge to the oracle minus exactly those updates."""
    want = _fault_free(WORLD, 4, 2, skip={VICTIM: 2})
    schedule = KillSchedule(
        [KillSpec("drain-commit", at=1, rank=VICTIM)], world=WORLD
    )
    results = {}

    def body(g):
        kg = KillGroup(g, schedule)
        rg = ResilientGroup(kg, timeout=20.0, retries=0, policy="quorum")
        t = MetricTable("ctr", shard=ShardContext(g.rank, WORLD))
        sess = ElasticSession(
            {"t": t}, str(tmp_path), process_group=rg, interval=10**9,
            fault_hook=schedule.fault_hook,
        )
        domain = FailureDomain({"t": t}, rg, session=sess, detect_after=2)
        try:
            for step in range(2):
                t.ingest(*_batch(step, g.rank))
                sess.step_done()
            schedule.check("drain-commit", g.rank)  # visit 0: all live
            domain.drain()
            sess.snapshot()  # the committed boundary
            for step in range(2, 4):
                t.ingest(*_batch(step, g.rank))
                sess.step_done()
            schedule.check("drain-commit", g.rank)  # visit 1: victim dies
            for _ in range(2):
                sync_and_compute(t, rg)
            assert domain.poll() == (VICTIM,)
            loss = domain.recover()
            assert not loss.exact
            assert loss.steps == 2 and loss.epochs == 0
            assert loss.generation == 0
            results[g.rank] = domain.drain()["t"].compute().as_dict()
            domain.close()
        except InjectedCrash:
            return

    ThreadWorld(WORLD).run(body)
    assert sorted(results) == [0, 1, 3]
    for rank, vals in results.items():
        _assert_same(vals, want, f"survivor rank {rank}")


def test_drain_after_snapshot_strips_dead_outbox(tmp_path):
    """Snapshot BEFORE a drain, then drain, then kill: the dead shard's
    outbox entries were already delivered to the survivors at that
    drain, so reconstruction must strip them (epoch-lag gate) instead of
    folding them twice. With no victim-owned keys in play the recovery
    loses nothing in VALUE (the bound still honestly declares the one
    epoch of lag) and the survivors match the fault-free oracle
    bit-identically — a double-count fails this equality loudly."""
    pool = np.arange(200)
    pool = pool[owner_of(hash_keys(pool.astype(np.uint64)), WORLD) != VICTIM]
    assert len(pool) > 100
    want = _fault_free(WORLD, 2, 2, pool=pool)
    schedule = KillSchedule(
        [KillSpec("drain-commit", at=1, rank=VICTIM)], world=WORLD
    )
    results = {}

    def body(g):
        kg = KillGroup(g, schedule)
        rg = ResilientGroup(kg, timeout=20.0, retries=0, policy="quorum")
        t = MetricTable("ctr", shard=ShardContext(g.rank, WORLD))
        sess = ElasticSession(
            {"t": t}, str(tmp_path), process_group=rg, interval=10**9,
            fault_hook=schedule.fault_hook,
        )
        domain = FailureDomain({"t": t}, rg, session=sess, detect_after=2)
        try:
            for step in range(2):
                t.ingest(*_batch(step, g.rank, pool=pool))
            sess.snapshot()  # gen 0: epoch 0, outboxes still undrained
            schedule.check("drain-commit", g.rank)  # visit 0: all live
            domain.drain()  # delivers the victim's outbox to survivors
            schedule.check("drain-commit", g.rank)  # visit 1: victim dies
            for _ in range(2):
                sync_and_compute(t, rg)
            assert domain.poll() == (VICTIM,)
            loss = domain.recover()
            assert loss.epochs == 1 and not loss.exact
            assert loss.generation == 0
            results[g.rank] = domain.drain()["t"].compute().as_dict()
            domain.close()
        except InjectedCrash:
            return

    ThreadWorld(WORLD).run(body)
    assert sorted(results) == [0, 1, 3]
    for rank, vals in results.items():
        _assert_same(vals, want, f"survivor rank {rank}")


# ---------------------------------------------------------------------------
# The crash matrix: every kill point × both snapshot writer modes
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("point", KILL_POINTS)
@pytest.mark.parametrize("async_writer", [False, True], ids=["sync", "async"])
def test_kill_point_crash_matrix(tmp_path, point, async_writer):
    """One serving step visits every kill point; visit 0 is healthy and
    commits a generation, visit 1 kills the victim at the parametrized
    point. The survivors must detect, recover, serve coherent flight +
    observability gathers on the REFORMED group, and after live rejoin
    the full world round-trips an elastic snapshot bit-identically."""
    from torcheval_tpu.obs.export import gather_observability
    from torcheval_tpu.obs.flight import gather_flight

    schedule = KillSchedule(
        [KillSpec(point, at=1, rank=VICTIM)], world=WORLD
    )
    rejoin_barrier = threading.Barrier(WORLD)
    bus = InProcessLinkBus()
    results = {}

    def body(g):
        kg = KillGroup(g, schedule)
        rg = ResilientGroup(kg, timeout=20.0, retries=0, policy="quorum")
        t = MetricTable("ctr", shard=ShardContext(g.rank, WORLD))
        sess = ElasticSession(
            {"t": t}, str(tmp_path), process_group=rg, interval=10**9,
            async_writer=async_writer, fault_hook=schedule.fault_hook,
        )
        plane = SyncPlane(
            {"mean": M.Mean()}, rg, interval=None, policy="quorum"
        )
        fcoll = {"s": M.Sum()}
        fed = Federation(rg, REGIONS_2X2, transport=bus, policy="quorum")
        domain = FailureDomain(
            {"t": t}, rg, session=sess, plane=plane, federation=fed,
            detect_after=2,
        )

        def serving_step(step):
            t.ingest(*_batch(step, g.rank, n=8))
            plane.metrics["mean"].update(np.float32(step + g.rank))
            plane.publish()
            schedule.check("plane-round", g.rank)
            try:
                plane.run_round()
            except Exception:
                pass  # degraded round right after the kill: retried
            schedule.check("drain-commit", g.rank)
            domain.drain()
            fcoll["s"].update(np.float32(1.0))
            schedule.check("federation-exchange", g.rank)
            try:
                fed.federate(fcoll)
            except Exception:
                pass  # degraded exchange right after the kill
            try:
                # snapshot-shard rendezvous rides the elastic fault hook
                sess.snapshot()
                sess.drain()
            except Exception:
                pass  # survivors' torn commit simply fails, retried later

        try:
            for step in range(2):
                serving_step(step)
            # --- survivors only past this line ---
            for _ in range(2):
                sync_and_compute(t, rg)
            assert domain.poll() == (VICTIM,)
            loss = domain.recover()
            assert domain.state == "recovered"
            assert domain.survivors == (0, 1, 3)
            assert loss.ranks == (VICTIM,)
            # diagnosis channels serve coherently on the REFORMED group
            rep = gather_observability(domain.group)
            fl = gather_flight(domain.group)
            assert rep["world_size"] == 3 and fl["world_size"] == 3
            assert sorted(rep["per_rank"]) == [0, 1, 2]
            domain.drain()
            if g.rank == 0:
                schedule.revive(VICTIM)
        except InjectedCrash:
            schedule.revival.wait(30.0)
            rejoin_barrier.wait(30.0)
            domain.rejoin(dead_ranks=(VICTIM,))
        else:
            rejoin_barrier.wait(30.0)
            domain.rejoin()
        assert domain.state == "armed"
        vals = domain.drain()["t"].compute().as_dict()
        # post-rejoin elastic round-trip at the full world: fresh
        # sessions (the victim's writer carries process-death semantics)
        sess2 = ElasticSession(
            {"t": t}, str(tmp_path), process_group=rg, interval=10**9
        )
        sess2.snapshot()
        # the leader writes MANIFEST.json after the digest gather; a
        # restore normally follows a restart, so line the world up
        # before reading the commit record back
        rejoin_barrier.wait(30.0)
        t2 = MetricTable("ctr", shard=ShardContext(g.rank, WORLD))
        sess3 = ElasticSession(
            {"t": t2}, str(tmp_path), process_group=rg, interval=10**9
        )
        restored = sess3.restore()
        assert restored is not None and restored.world_size == WORLD
        restored_vals = sync_and_compute(t2, rg).as_dict()
        _assert_same(restored_vals, vals, f"round-trip rank {g.rank}")
        results[g.rank] = vals
        domain.close()

    ThreadWorld(WORLD).run(body)
    assert sorted(results) == list(range(WORLD))
    assert schedule.killed == [(point, 1, VICTIM)]
    want = results[0]
    for rank in range(1, WORLD):
        _assert_same(results[rank], want, f"agreement rank {rank}")


# ---------------------------------------------------------------------------
# ThreadWorld-8 soak: federation + plane + overload + link chaos
# ---------------------------------------------------------------------------


class _Counting:
    """Delegating group wrapper counting FULL-WORLD collectives only
    (subgroups reach the inner group via ``__getattr__``, uncounted) —
    the zero-collectives-on-the-serving-path pin for detection."""

    def __init__(self, inner):
        self._inner = inner
        self.calls = 0

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def allgather_object(self, obj):
        self.calls += 1
        return self._inner.allgather_object(obj)

    def allgather_array(self, x):
        self.calls += 1
        return self._inner.allgather_array(x)


def test_soak_leader_kill_mid_exchange_world8(tmp_path):
    """Two regions × 4 under overload traffic and link-delay chaos; the
    EU region leader (rank 4) dies mid federation exchange on a
    generation boundary. Detection issues zero full-world collectives,
    leadership fails over to rank 5, admission outbox budgets rescale
    7/8 → back, and the rejoined world is bit-identical to the
    fault-free oracle."""
    from torcheval_tpu.table._admission import (
        AdmissionController,
        ServingBudget,
    )

    world8, victim, steps = 8, 4, 3
    load = [
        OverloadSchedule.sustained(steps, 64.0, seed=r, family="ctr")
        for r in range(world8)
    ]

    def oracle_body(g):
        t = MetricTable("ctr", shard=ShardContext(g.rank, world8))
        for step in range(steps):
            b = load[g.rank].batch(step)
            t.ingest(b.keys, **b.kwargs)
            adopt_synced(t, g)
        for _ in range(2):
            adopt_synced(t, g)
        return sync_and_compute(t, g).as_dict()

    want = ThreadWorld(world8).run(oracle_body)[0]

    schedule = KillSchedule(
        [KillSpec("federation-exchange", at=2, rank=victim)], world=world8
    )
    rejoin_barrier = threading.Barrier(world8)
    chaos = ChaosLinkTransport(
        InProcessLinkBus(), jitter_polls=(0, 2), seed=11
    )
    results, leader_flags, outbox_budgets = {}, {}, {}

    def body(g):
        cg = _Counting(g)
        kg = KillGroup(cg, schedule)
        rg = ResilientGroup(kg, timeout=20.0, retries=0, policy="quorum")
        t = MetricTable(
            "ctr",
            shard=ShardContext(g.rank, world8),
            # headroom budgets: the overload batches route ~3.6k foreign
            # rows per drain, and the ladder must stay at rung 0 — an
            # armed sampling rung HT-reweights values, which is correct
            # but breaks the bit-identity oracle this soak pins
            admission=AdmissionController(
                ServingBudget(max_keys=65536, max_outbox=8192)
            ),
        )
        sess = ElasticSession(
            {"t": t}, str(tmp_path), process_group=rg, interval=10**9,
            fault_hook=schedule.fault_hook,
        )
        plane = SyncPlane(
            {"mean": M.Mean()}, rg, interval=None, policy="quorum"
        )
        fcoll = {"s": M.Sum()}
        fed = Federation(rg, REGIONS_4X2, transport=chaos, policy="quorum")
        domain = FailureDomain(
            {"t": t}, rg, session=sess, plane=plane, federation=fed,
            detect_after=2,
        )
        try:
            for step in range(steps):
                b = load[g.rank].batch(step)
                t.ingest(b.keys, **b.kwargs)
                plane.metrics["mean"].update(np.float32(step))
                plane.publish()
                schedule.check("plane-round", g.rank)
                plane.run_round()
                schedule.check("drain-commit", g.rank)
                domain.drain()
                sess.snapshot()  # boundary commit BEFORE the exchange
                fcoll["s"].update(np.float32(1.0))
                schedule.check("federation-exchange", g.rank)
                try:
                    fed.federate(fcoll)
                except Exception:
                    pass  # dead-leader exchange right after the kill
            # --- survivors only past this line (kill at step 2) ---
            # a kill-point rendezvous doubles as a survivors-only
            # barrier: every live rank enters detection in lockstep
            schedule.check("plane-round", g.rank)
            before = cg.calls
            for _ in range(2):
                sync_and_compute(t, rg)  # quorum detours, not full-world
            assert domain.poll() == (victim,)
            loss = domain.recover()
            # the detect/recover epoch never touched the full world
            assert cg.calls == before, (g.rank, cg.calls - before)
            assert loss.exact, loss
            assert domain.survivors == (0, 1, 2, 3, 5, 6, 7)
            leader_flags[g.rank] = (fed.is_leader, fed.my_region.name)
            outbox_budgets[g.rank] = t._admission.budget.max_outbox
            # ladder calm throughout: no HT reweighting touched the data
            assert int(t.admission_rung) == 0, int(t.admission_rung)
            assert int(t.shed_rows_total) == 0, int(t.shed_rows_total)
            domain.drain()
            if g.rank == 0:
                schedule.revive(victim)
        except InjectedCrash:
            schedule.revival.wait(30.0)
            rejoin_barrier.wait(30.0)
            domain.rejoin(dead_ranks=(victim,))
        else:
            rejoin_barrier.wait(30.0)
            domain.rejoin()
        assert domain.state == "armed"
        # the reformed-back plane serves full-world rounds again
        plane.metrics["mean"].update(np.float32(1.0))
        plane.publish()
        version = plane.run_round()
        assert version is not None and version >= 1
        results[g.rank] = domain.drain()["t"].compute().as_dict()
        domain.close()

    ThreadWorld(world8).run(body)

    assert sorted(results) == list(range(world8))
    for rank, vals in results.items():
        _assert_same(vals, want, f"soak rank {rank}")
    # leader failover: lowest surviving EU rank took the region over
    assert leader_flags[5] == (True, "eu")
    assert leader_flags[6][0] is False and leader_flags[7][0] is False
    assert leader_flags[0] == (True, "us")
    # admission outbox budget rescaled to the 7-rank world...
    assert all(
        outbox_budgets[r] == 8025 for r in (0, 1, 2, 3, 5, 6, 7)
    ), outbox_budgets
    # ...and back at rejoin (checked on the live controller post-run is
    # racy across threads, so pin the arithmetic directly)
    ctrl = AdmissionController(ServingBudget(max_outbox=8025))
    ctrl.rescale_world(7, 8)
    assert ctrl.budget.max_outbox == 8192


# ---------------------------------------------------------------------------
# Detection contract
# ---------------------------------------------------------------------------


def test_poll_is_local_and_respects_detect_after():
    """poll() reads local health only (zero collectives) and confirms
    nothing until the missing streak reaches ``detect_after``; a single
    missed sync stays a transient."""
    schedule = KillSchedule(
        [KillSpec("drain-commit", at=0, rank=VICTIM)], world=WORLD
    )
    states = {}

    def body(g):
        cg = _Counting(g)
        kg = KillGroup(cg, schedule)
        rg = ResilientGroup(kg, timeout=20.0, retries=0, policy="quorum")
        t = MetricTable("ctr", shard=ShardContext(g.rank, WORLD))
        domain = FailureDomain({"t": t}, rg, detect_after=3)
        try:
            t.ingest(*_batch(0, g.rank))
            schedule.check("drain-commit", g.rank)  # victim dies now
            seen = []
            for _ in range(3):
                base = cg.calls
                dead = domain.poll()
                assert cg.calls == base  # detection is collective-free
                seen.append(dead)
                sync_and_compute(t, rg)
            seen.append(domain.poll())
            states[g.rank] = seen
            domain.close()
        except InjectedCrash:
            return

    ThreadWorld(WORLD).run(body)
    for rank, seen in states.items():
        # streak 0, 1, 2 → transient; streak 3 → confirmed
        assert seen == [(), (), (), (VICTIM,)], (rank, seen)


def test_note_failure_external_signal_and_recover_guard():
    """note_failure() accepts an out-of-band death report (a federation
    dark-region probe, an orchestrator signal); recover() refuses to run
    outside the degraded state; self-condemnation is a no-op."""
    g = ThreadWorld(1).views[0]
    t = MetricTable("ctr", shard=ShardContext(0, 1))
    domain = FailureDomain({"t": t}, g)
    try:
        with pytest.raises(RuntimeError, match="confirmed loss"):
            domain.recover()
        assert domain.note_failure((0,)) == ()  # own rank: no-op
        assert domain.state == "armed"
    finally:
        domain.close()


# ---------------------------------------------------------------------------
# Satellites: staleness budgets, reservoir, gauges, CI targets
# ---------------------------------------------------------------------------


def test_tenant_staleness_budget_knob_env_and_exchange_interval():
    import gc

    from torcheval_tpu.config import _env_int

    with pytest.raises(ValueError, match="staleness_epochs"):
        MetricTable("ctr", staleness_epochs=-1)
    with pytest.raises(ValueError):
        config.set_tenant_staleness_epochs(-2)
    # the config default stamps tables constructed without an explicit
    # budget; the env knob feeds the same default at import
    assert _env_int("TORCHEVAL_TPU_TENANT_STALENESS", 0, minimum=0) == 0
    import os

    os.environ["TORCHEVAL_TPU_TENANT_STALENESS"] = "7"
    try:
        assert (
            _env_int("TORCHEVAL_TPU_TENANT_STALENESS", 0, minimum=0) == 7
        )
    finally:
        del os.environ["TORCHEVAL_TPU_TENANT_STALENESS"]
    config.set_tenant_staleness_epochs(5)
    try:
        t_default = MetricTable("ctr")
        assert t_default.staleness_epochs == 5
    finally:
        config.set_tenant_staleness_epochs(0)

    # the tightest LIVE budget wins; unbudgeted tables contribute none
    del t_default
    gc.collect()
    base = tightest_staleness_budget()
    t3 = MetricTable("ctr", staleness_epochs=3)
    assert tightest_staleness_budget() == 3
    t2 = MetricTable("ctr", staleness_epochs=2)
    assert tightest_staleness_budget() == 2

    # Federation.exchange_interval honors it (floor 1, capped at base)
    fed = Federation(
        ThreadWorld(2).views[0],
        [("us", (0,)), ("eu", (1,))],
        transport=InProcessLinkBus(),
    )
    assert fed.exchange_interval(8) == 2
    del t2
    gc.collect()
    assert tightest_staleness_budget() == 3
    assert fed.exchange_interval(8) == 3
    assert fed.exchange_interval(2) == 2  # never stretched past base
    del t3
    gc.collect()
    assert tightest_staleness_budget() == base


def test_priority_reservoir_weighted_and_deterministic():
    """The online priority-key reservoir: refreshed at drain commit,
    weight-biased (splitmix64 exponential jitter — no RNG state), and
    bit-identically reproducible across runs."""
    from torcheval_tpu.table._admission import (
        AdmissionController,
        ServingBudget,
    )

    with pytest.raises(ValueError, match="priority_reservoir"):
        AdmissionController(
            ServingBudget(max_keys=16), priority_reservoir=-1
        )

    def run():
        g = ThreadWorld(1).views[0]
        t = MetricTable(
            "ctr",
            shard=ShardContext(0, 1),
            admission=AdmissionController(
                ServingBudget(max_keys=4096), priority_reservoir=4
            ),
        )
        keys = np.arange(50)
        t.ingest(
            keys, np.ones(50, np.float32), np.ones(50, np.float32)
        )
        heavy = np.full(200, 7)
        t.ingest(
            heavy, np.ones(200, np.float32), np.ones(200, np.float32)
        )
        adopt_synced(t, g)
        return np.asarray(t._admission._priority_hashes).copy()

    first, second = run(), run()
    assert np.array_equal(first, second)
    assert len(first) == 4
    assert hash_keys(np.asarray([7], np.uint64))[0] in first
    assert np.array_equal(first, np.sort(first))


def test_resilience_counter_source_and_prometheus_grammar(rec):
    """Arming a domain registers the ``resilience`` counter source:
    numeric-only gauges that render under the pinned Prometheus
    exposition grammar."""
    import re

    from torcheval_tpu.obs.counters import default_registry
    from torcheval_tpu.obs.export import render_prometheus

    prom_line = re.compile(
        r"^(?:# (?:TYPE|HELP) [a-zA-Z_][a-zA-Z0-9_]* \w+$"
        r"|[a-zA-Z_][a-zA-Z0-9_]*"
        r"(?:\{[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\\n]|\\\\|\\\"|\\n)*\""
        r"(?:,[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\\n]|\\\\|\\\"|\\n)*\")*\})?"
        r" [0-9.eEinf+-]+(?:$|\s))"
    )
    g = ThreadWorld(1).views[0]
    t = MetricTable("ctr", shard=ShardContext(0, 1))
    domain = FailureDomain({"t": t}, g)
    try:
        assert "resilience" in default_registry().sources
        reading = default_registry().read()["resilience"]
        for key in (
            "armed", "state", "dead_ranks", "survivor_world",
            "detections", "recoveries", "rejoins", "reformed_to_size",
            "consecutive_missing", "loss_steps", "loss_epochs",
            "loss_exact",
        ):
            assert key in reading, key
            assert isinstance(reading[key], (int, float)), key
        assert reading["armed"] == 1 and reading["survivor_world"] == 1
        text = render_prometheus()
        assert "torcheval_tpu_resilience_armed 1" in text
        assert "torcheval_tpu_resilience_survivor_world 1" in text
        for line in text.splitlines():
            if line:
                assert prom_line.match(line), line
    finally:
        domain.close()
    assert "resilience" not in default_registry().sources


def test_failover_in_concurrency_default_targets():
    from torcheval_tpu.analysis.concurrency import DEFAULT_TARGETS

    assert "failover.py" in DEFAULT_TARGETS
