"""Worker for the FAST-tier multi-process sync smoke.

A deliberately tiny sibling of ``_multihost_worker.py`` (which carries the
full slow-tier archetype matrix): two metrics only — a counter-state metric
(fused psum-style sum sync) and a buffered metric (padded ragged gather) —
so the default test tier exercises a real spawn + ``MultiHostGroup`` wire
without the matrix's wall-clock. Reference bar: the class tester's spawned
gloo workers (reference utils/test_utils/metric_class_tester.py:292-341).
"""

from __future__ import annotations

import json


def main() -> None:
    import jax

    from torcheval_tpu.launcher import init_from_env

    init_from_env()
    rank = jax.process_index()

    import numpy as np

    from torcheval_tpu.distributed import MultiHostGroup, default_process_group
    from torcheval_tpu.metrics import BinaryAUROC, MulticlassAccuracy
    from torcheval_tpu.metrics.toolkit import sync_and_compute

    group = default_process_group()
    assert isinstance(group, MultiHostGroup), type(group)

    results = {"nproc": group.world_size, "rank": group.rank}

    # counter state: rank-dependent correct/total counts
    acc = MulticlassAccuracy()
    rng = np.random.default_rng(100 + rank)
    n = 8 + 4 * rank  # asymmetric batch sizes
    scores = rng.uniform(size=(n, 4)).astype(np.float32)
    labels = rng.integers(0, 4, size=n)
    acc.update(scores, labels)
    results["accuracy"] = float(sync_and_compute(acc, group))

    # buffered state: ragged per-rank buffers cross the padded gather
    auroc = BinaryAUROC()
    s = rng.uniform(size=n).astype(np.float32)
    t = (rng.random(n) < 0.5).astype(np.float32)
    auroc.update(s, t)
    results["auroc"] = float(sync_and_compute(auroc, group))

    print("RESULT " + json.dumps(results), flush=True)


if __name__ == "__main__":
    main()
