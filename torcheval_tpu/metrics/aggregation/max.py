"""Max class metric.

Parity: reference torcheval/metrics/aggregation/max.py:19-63.
"""

from __future__ import annotations

from typing import TypeVar

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.metric import MergeKind, Metric

TMax = TypeVar("TMax", bound="Max")


@jax.jit
def _max_update_jit(state: jax.Array, input: jax.Array) -> jax.Array:
    # one fused dispatch: reduce + running-max accumulate
    return jnp.maximum(state, jnp.max(input))


class Max(Metric[jax.Array]):
    """Running maximum over all elements of all updates.

    Examples::

        >>> from torcheval_tpu.metrics import Max
        >>> Max().update(jnp.array([1., 5., 2.])).compute()
        Array(5., dtype=float32)
    """

    def __init__(self, *, device=None) -> None:
        super().__init__(device=device)
        self._add_state("max", jnp.float32(-jnp.inf), merge=MergeKind.MAX)

    def update(self: TMax, input) -> TMax:
        self.max = _max_update_jit(self.max, self._input_float(input))
        return self

    def compute(self) -> jax.Array:
        return self.max
