"""RetrievalPrecision class metric (multi-query precision @ k).

Parity: reference torcheval/metrics/ranking/retrieval_precision.py:26-199.
State is a *bounded* per-query buffer: after each update only the running
top-k scores (and their labels) are kept (reference update_single_query
:142-149), so the buffer never exceeds ``k`` entries per query — TPU-friendly
by construction. Merge is per-query concatenation (reference :181-199);
``compute`` re-ranks the merged buffers.
"""

from __future__ import annotations

from typing import Iterable, Optional, TypeVar, Union

import jax
import jax.numpy as jnp
import numpy as np

from torcheval_tpu.metrics.functional.ranking.retrieval_precision import (
    _retrieval_precision_compute,
    _retrieval_precision_param_check,
    _retrieval_precision_update_input_check,
    get_topk,
)
from torcheval_tpu.metrics.metric import MergeKind, Metric

TRetrievalPrecision = TypeVar("TRetrievalPrecision", bound="RetrievalPrecision")


class RetrievalPrecision(Metric[jax.Array]):
    """Retrieval precision @ k over one or more query streams.

    Args:
        empty_target_action: behavior for queries whose targets contain no
            positive: ``neg`` -> 0.0, ``pos`` -> 1.0, ``skip`` -> NaN,
            ``err`` -> raise.
        k: number of retrieved elements considered (None = all).
        limit_k_to_size: clamp k to the buffered size.
        num_queries: number of independent query streams; updates route
            samples with the ``indexes`` argument.
        avg: ``macro`` averages over queries; ``none``/None returns the
            per-query vector.

    Examples::

        >>> import jax.numpy as jnp
        >>> from torcheval_tpu.metrics import RetrievalPrecision
        >>> metric = RetrievalPrecision(k=2)
        >>> metric.update(jnp.array([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.2]),
        ...               jnp.array([0, 0, 1, 1, 1, 0, 1]))
        >>> metric.compute()
        Array([0.5], dtype=float32)
    """

    def __init__(
        self,
        empty_target_action: str = "neg",
        k: Optional[int] = None,
        limit_k_to_size: bool = False,
        num_queries: int = 1,
        avg: Optional[str] = None,
        device: Optional[jax.Device] = None,
    ) -> None:
        _retrieval_precision_param_check(k, limit_k_to_size)
        if empty_target_action not in ("neg", "pos", "skip", "err"):
            raise ValueError(
                "empty_target_action must be one of 'neg', 'pos', 'skip', "
                f"'err', got {empty_target_action}."
            )
        if avg not in ("macro", "none", None):
            raise ValueError(f"avg must be 'macro', 'none' or None, got {avg}.")
        super().__init__(device=device)
        self.empty_target_action = empty_target_action
        self.num_queries = num_queries
        self.k = k
        self.limit_k_to_size = limit_k_to_size
        self.avg = avg
        self._add_state(
            "topk", [jnp.zeros(0) for _ in range(num_queries)], merge=MergeKind.CUSTOM
        )
        self._add_state(
            "target", [jnp.zeros(0) for _ in range(num_queries)], merge=MergeKind.CUSTOM
        )

    def update(
        self: TRetrievalPrecision,
        input,
        target,
        indexes=None,
    ) -> TRetrievalPrecision:
        """Accumulate scores/labels, routed per query by ``indexes``."""
        input, target = self._input(input), self._input(target)
        _retrieval_precision_update_input_check(input, target)
        if self.num_queries == 1:
            self._update_single_query(0, input, target)
            return self
        if indexes is None:
            raise ValueError(
                "`indexes` must be passed during update() when num_queries > 1."
            )
        # query routing is data-dependent (dynamic shapes) -> eager host-side
        # partition, as in the reference's Python loop (reference :134-140)
        # out-of-range indexes are ignored, as in the reference's
        # `for i in range(num_queries): if i in indexes` loop (reference :138)
        idx = np.asarray(indexes)
        for i in np.unique(idx):
            if 0 <= i < self.num_queries:
                mask = idx == i
                self._update_single_query(int(i), input[mask], target[mask])
        return self

    def _update_single_query(self, i: int, input: jax.Array, target: jax.Array) -> None:
        batch_preds = jnp.concatenate([self.topk[i], input.astype(jnp.float32)])
        batch_targets = jnp.concatenate([self.target[i], target.astype(jnp.float32)])
        topk_vals, topk_idx = get_topk(batch_preds, self.k)
        self.topk[i] = topk_vals
        self.target[i] = jnp.take_along_axis(batch_targets, topk_idx, axis=-1)

    def compute(self) -> jax.Array:
        """Per-query retrieval precision, or its macro average."""
        rp = []
        for i in range(self.num_queries):
            if self.target[i].shape[-1] == 0:
                rp.append(jnp.array([jnp.nan]))
            elif not bool(jnp.any(self.target[i] == 1)):
                if self.empty_target_action == "pos":
                    rp.append(jnp.array([1.0]))
                elif self.empty_target_action == "neg":
                    rp.append(jnp.array([0.0]))
                elif self.empty_target_action == "skip":
                    rp.append(jnp.array([jnp.nan]))
                else:  # "err"
                    raise ValueError(
                        f"no positive value found in target={self.target[i]}."
                    )
            else:
                rp.append(
                    _retrieval_precision_compute(
                        self.topk[i], self.target[i], self.k, self.limit_k_to_size
                    ).reshape(-1)
                )
        result = jnp.concatenate(rp)
        if self.avg == "macro":
            return jnp.nanmean(result)
        return result

    def merge_state(
        self: TRetrievalPrecision, metrics: Iterable[TRetrievalPrecision]
    ) -> TRetrievalPrecision:
        """Per-query buffer concatenation (reference :181-199)."""
        metrics = list(metrics)
        for i in range(self.num_queries):
            self.topk[i] = jnp.concatenate(
                [self.topk[i]]
                + [jax.device_put(m.topk[i], self._device) for m in metrics]
            )
            self.target[i] = jnp.concatenate(
                [self.target[i]]
                + [jax.device_put(m.target[i], self._device) for m in metrics]
            )
        return self
