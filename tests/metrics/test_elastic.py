"""Crash matrix for elastic evaluation (ISSUE 4).

Every injected two-phase-commit crash point (pre-shard, mid-shard,
pre-manifest, post-manifest) and filesystem fault (truncated shard,
corrupted shard bytes, corrupted manifest digest) must leave a bundle
from which ``ElasticSession.restore()`` + continued (fenced) updates
produce ``compute()`` results BIT-IDENTICAL to the uninterrupted run —
with no batch double-counted and no partial generation ever loaded.
World-size-change resumes (4→2 and 2→4 over ``ThreadWorld``) redistribute
per-rank states through ``merge_state`` and must match the same-order
merge oracle exactly. Survivor re-formation: after N consecutive syncs
missing the same ranks, ``ResilientGroup`` re-forms onto the survivors
and subsequent syncs run undegraded with subgroup-relative provenance.
"""

from __future__ import annotations

import os
import warnings

import numpy as np
import pytest

from torcheval_tpu.elastic import CRASH_POINTS, ElasticSession
from torcheval_tpu.metrics import BinaryAUROC, MulticlassAccuracy
from torcheval_tpu.metrics.toolkit import (
    clone_metric,
    get_synced_metric,
    sync_and_compute,
)
from torcheval_tpu.resilience import ResilientGroup
from torcheval_tpu.utils.test_utils import (
    FaultInjectionGroup,
    InjectedCrash,
    SnapshotCrashPlan,
    ThreadWorld,
    corrupt_manifest_digest,
    corrupt_shard,
    truncate_shard,
)

STEPS = 10
INTERVAL = 3


def _batches(seed: int, steps: int = STEPS):
    rng = np.random.default_rng(seed)
    return [
        (
            np.float32(rng.uniform(size=(8, 4))),
            rng.integers(0, 4, 8),
        )
        for _ in range(steps)
    ]


def _fresh():
    return {"acc": MulticlassAccuracy(), "auroc": BinaryAUROC()}


def _feed(metrics, batch):
    scores, target = batch
    metrics["acc"].update(scores, target)
    metrics["auroc"].update(scores[:, 0], (target == 0).astype(np.float32))


def _values(metrics):
    return {k: np.asarray(m.compute()) for k, m in metrics.items()}


def _assert_bit_identical(got, want):
    for name in want:
        assert np.array_equal(got[name], want[name]), name


def _oracle(batches):
    metrics = _fresh()
    for batch in batches:
        _feed(metrics, batch)
    return _values(metrics)


def _resume_and_finish(directory, batches, *, interval=INTERVAL):
    """A 'restarted process': fresh metrics, restore, fenced replay."""
    metrics = _fresh()
    session = ElasticSession(metrics, directory, interval=interval)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        restored = session.restore()
    for step, batch in enumerate(batches):
        if not session.fence(step):
            continue
        _feed(metrics, batch)
        session.step_done(step)
    session.close()
    return metrics, restored


# ------------------------------------------------------------ crash matrix


@pytest.mark.parametrize("point", CRASH_POINTS)
@pytest.mark.parametrize("at_snapshot", [0, 1])
def test_crash_matrix_resumes_bit_identical(tmp_path, point, at_snapshot):
    batches = _batches(11)
    metrics = _fresh()
    plan = SnapshotCrashPlan(point, at_snapshot=at_snapshot)
    session = ElasticSession(
        metrics, str(tmp_path), interval=INTERVAL, fault_hook=plan
    )
    with pytest.raises(InjectedCrash):
        for step, batch in enumerate(batches):
            _feed(metrics, batch)
            session.step_done(step)
    assert plan.crashed

    resumed, restored = _resume_and_finish(str(tmp_path), batches)
    _assert_bit_identical(_values(resumed), _oracle(batches))
    # no batch double-counted: the sample count equals the oracle's
    assert resumed["auroc"].num_samples == STEPS * 8
    # a crash before the FIRST commit means a fresh start, never garbage
    committed_any = point == "post-manifest" or at_snapshot > 0
    assert (restored is not None) == committed_any


def test_no_partial_generation_is_ever_loaded(tmp_path):
    """A crash between shard write and manifest commit leaves an
    UNCOMMITTED generation: restore must not touch it, even though its
    shard file is fully written and internally consistent."""
    batches = _batches(12)
    metrics = _fresh()
    plan = SnapshotCrashPlan("pre-manifest", at_snapshot=1)
    session = ElasticSession(
        metrics, str(tmp_path), interval=INTERVAL, fault_hook=plan
    )
    with pytest.raises(InjectedCrash):
        for step, batch in enumerate(batches):
            _feed(metrics, batch)
            session.step_done(step)
    gen_dirs = sorted(p for p in os.listdir(tmp_path) if p.startswith("gen-"))
    assert len(gen_dirs) == 2  # gen 0 committed, gen 1 torn
    assert not os.path.exists(tmp_path / gen_dirs[1] / "MANIFEST.json")

    _, restored = _resume_and_finish(str(tmp_path), batches)
    assert restored is not None and restored.generation == 0
    assert restored.step == INTERVAL  # the committed cursor, not the torn one


@pytest.mark.parametrize(
    "fault",
    [
        lambda d, g: truncate_shard(d, g),
        lambda d, g: corrupt_shard(d, g),
        lambda d, g: corrupt_manifest_digest(d, g),
    ],
    ids=["truncated-shard", "corrupt-shard", "corrupt-manifest-digest"],
)
def test_fs_fault_falls_back_one_generation(tmp_path, fault):
    batches = _batches(13)
    metrics = _fresh()
    session = ElasticSession(
        metrics, str(tmp_path), interval=INTERVAL, retention=3
    )
    for step, batch in enumerate(batches):
        _feed(metrics, batch)
        session.step_done(step)
    session.close()
    newest = max(
        int(p.split("-")[1]) for p in os.listdir(tmp_path) if p.startswith("gen-")
    )
    fault(str(tmp_path), newest)

    resumed, restored = _resume_and_finish(str(tmp_path), batches)
    assert restored is not None and restored.generation == newest - 1
    _assert_bit_identical(_values(resumed), _oracle(batches))
    assert resumed["auroc"].num_samples == STEPS * 8


def test_double_resume_counts_nothing_twice(tmp_path):
    """Resume, crash again BEFORE any new snapshot, resume again: the
    second resume restores the same generation and the fence still admits
    every uncovered batch exactly once."""
    batches = _batches(14)
    metrics = _fresh()
    session = ElasticSession(metrics, str(tmp_path), interval=INTERVAL)
    for step, batch in enumerate(batches[:5]):
        _feed(metrics, batch)
        session.step_done(step)
    session.close()

    # first resume: process ONE more step, then "die" (no snapshot: the
    # interval is not due)
    m1 = _fresh()
    s1 = ElasticSession(m1, str(tmp_path), interval=INTERVAL)
    r1 = s1.restore()
    assert r1.step == INTERVAL
    _feed(m1, batches[r1.step])
    s1.step_done(r1.step)

    # second resume: same generation, full fenced replay
    resumed, r2 = _resume_and_finish(str(tmp_path), batches)
    assert r2.generation == r1.generation and r2.step == r1.step
    _assert_bit_identical(_values(resumed), _oracle(batches))
    assert resumed["auroc"].num_samples == STEPS * 8


def test_out_of_order_step_is_rejected(tmp_path):
    metrics = _fresh()
    session = ElasticSession(metrics, str(tmp_path), interval=INTERVAL)
    for step, batch in enumerate(_batches(15)[:5]):
        _feed(metrics, batch)
        session.step_done(step)
    session.close()
    m2 = _fresh()
    s2 = ElasticSession(m2, str(tmp_path), interval=INTERVAL)
    s2.restore()
    with pytest.raises(RuntimeError, match="fence"):
        s2.step_done(0)  # already covered by the snapshot


def test_retention_rotates_old_generations(tmp_path):
    metrics = _fresh()
    session = ElasticSession(
        metrics, str(tmp_path), interval=2, retention=2
    )
    for step, batch in enumerate(_batches(16)):
        _feed(metrics, batch)
        session.step_done(step)
    session.close()
    gens = sorted(p for p in os.listdir(tmp_path) if p.startswith("gen-"))
    assert gens == ["gen-00000003", "gen-00000004"]  # newest 2 of 5


def test_restore_returns_none_on_fresh_directory(tmp_path):
    session = ElasticSession(_fresh(), str(tmp_path))
    assert session.restore() is None
    assert session.cursor == 0 and session.fence(0)


def test_payload_rides_the_bundle(tmp_path):
    metrics = _fresh()
    session = ElasticSession(metrics, str(tmp_path), interval=2)
    for step, batch in enumerate(_batches(17)[:4]):
        _feed(metrics, batch)
        session.step_done(step, payload={"iterator": step})
    session.close()
    s2 = ElasticSession(_fresh(), str(tmp_path), interval=2)
    restored = s2.restore()
    # the payload captured at the snapshot-triggering step
    assert restored.payload == {"iterator": 3}
    assert restored.payloads == ({"iterator": 3},)


def test_payload_is_retained_until_the_next_snapshot(tmp_path):
    """A payload passed on a NON-snapshot step must still ride the next
    snapshot — users only pass it when their iterator state changes."""
    metrics = _fresh()
    session = ElasticSession(metrics, str(tmp_path), interval=4)
    for step, batch in enumerate(_batches(20)[:4]):
        _feed(metrics, batch)
        # payload only on step 1; the interval fires at step 3
        session.step_done(
            step, payload={"it": 1} if step == 1 else None
        )
    session.close()
    restored = ElasticSession(_fresh(), str(tmp_path), interval=4).restore()
    assert restored.payload == {"it": 1}


def test_writer_recoverable_error_keeps_collective_lockstep(tmp_path):
    """A per-generation writer failure (ENOSPC-style Exception, not a
    crash) is ferried to the caller but the writer keeps attempting later
    queued generations — silently skipping them would desynchronize the
    digest gathers rank-wide."""
    batches = _batches(24)
    metrics = _fresh()
    session = ElasticSession(
        metrics, str(tmp_path), interval=INTERVAL, async_writer=True
    )
    real_write = session._write_bundle
    failed = []

    def flaky_write(generation, *args):
        if generation == 0 and not failed:
            failed.append(generation)
            raise OSError("no space left on device")
        return real_write(generation, *args)

    session._write_bundle = flaky_write
    session._writer._write_bundle = flaky_write
    ferried = []
    for step, batch in enumerate(batches):
        _feed(metrics, batch)
        try:
            session.step_done(step)
        except OSError as e:  # the loop logs the failed snapshot and keeps on
            ferried.append(e)
            # the ferried error raises BEFORE the cursor advance, so the
            # step is not yet counted: simply retry
            session.step_done(step)
    session.close()
    assert len(ferried) == 1 and "no space left" in str(ferried[0])
    # generation 0 failed, but LATER generations were still written
    committed = sorted(
        p for p in os.listdir(tmp_path)
        if p.startswith("gen-")
        and os.path.exists(tmp_path / p / "MANIFEST.json")
    )
    assert committed and committed[-1] > "gen-00000000"
    assert not os.path.exists(tmp_path / "gen-00000000" / "MANIFEST.json")


def test_local_replica_group_is_rejected(tmp_path):
    import jax

    from torcheval_tpu.distributed import LocalReplicaGroup

    group = LocalReplicaGroup(jax.local_devices()[:1])
    with pytest.raises(TypeError, match="LocalReplicaGroup"):
        ElasticSession(_fresh(), str(tmp_path), process_group=group)


# ------------------------------------------------------------- async mode


def test_async_snapshots_restore_bit_identical(tmp_path):
    batches = _batches(18)
    metrics = _fresh()
    with ElasticSession(
        metrics, str(tmp_path), interval=INTERVAL, async_writer=True
    ) as session:
        for step, batch in enumerate(batches[:7]):
            _feed(metrics, batch)
            session.step_done(step)
        session.drain()  # every queued generation is on disk now
    resumed, restored = _resume_and_finish(str(tmp_path), batches)
    assert restored is not None and restored.step == 6
    _assert_bit_identical(_values(resumed), _oracle(batches))


def test_async_crash_is_ferried_to_close(tmp_path):
    """A crash on the background writer (a preemption mid-write) must not
    vanish: the drain/close path re-raises it, and the on-disk state
    still resumes bit-identically."""
    batches = _batches(19)
    metrics = _fresh()
    plan = SnapshotCrashPlan("pre-manifest", at_snapshot=1)
    session = ElasticSession(
        metrics,
        str(tmp_path),
        interval=INTERVAL,
        async_writer=True,
        fault_hook=plan,
    )
    with pytest.raises(InjectedCrash):
        for step, batch in enumerate(batches):
            _feed(metrics, batch)
            session.step_done(step)
        session.close()
    assert plan.crashed

    resumed, restored = _resume_and_finish(str(tmp_path), batches)
    assert restored is not None and restored.generation == 0
    _assert_bit_identical(_values(resumed), _oracle(batches))


def test_restore_quarantines_unusable_newer_generations(tmp_path):
    """A committed-but-corrupt generation must not occupy a retention
    slot after a fallback restore — left in place, the next rotation
    could evict the very generation that just saved the run."""
    batches = _batches(21)
    metrics = _fresh()
    session = ElasticSession(
        metrics, str(tmp_path), interval=INTERVAL, retention=2
    )
    for step, batch in enumerate(batches):
        _feed(metrics, batch)
        session.step_done(step)
    session.close()
    newest = max(
        int(p.split("-")[1]) for p in os.listdir(tmp_path) if p.startswith("gen-")
    )
    corrupt_shard(str(tmp_path), newest)

    probe = ElasticSession(_fresh(), str(tmp_path), interval=INTERVAL)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        restored = probe.restore()
    assert restored.generation == newest - 1
    # the corrupt generation was quarantined (deleted) by the restore, so
    # the restored one cannot be rotated out by it — the number is then
    # free for the resumed run's next (valid) commit
    assert not os.path.exists(tmp_path / f"gen-{newest:08d}")
    probe.close()

    resumed, restored = _resume_and_finish(str(tmp_path), batches)
    assert restored.generation == newest - 1
    _assert_bit_identical(_values(resumed), _oracle(batches))


def test_generation_divergence_fails_loudly_at_commit(tmp_path):
    """Ranks that disagree on the next generation number (divergent
    directory scans) must fail the commit, not publish a manifest whose
    digests reference shards in another generation's directory."""
    directory = str(tmp_path)
    world = ThreadWorld(2)

    def body(g):
        metrics = _fresh()
        session = ElasticSession(
            metrics, directory, process_group=g, interval=100
        )
        if g.rank == 1:
            session._next_gen += 1  # simulate a divergent directory scan
        _feed(metrics, _batches(22)[0])
        session.step_done(0)
        if g.rank == 0:
            with pytest.raises(RuntimeError, match="generations \\[0, 1\\]"):
                session.snapshot()
        else:
            session.snapshot()  # non-leader: writes its shard, no commit
        return True

    assert world.run(body) == [True, True]


def test_async_snapshots_use_a_dedicated_communicator(tmp_path):
    """The async writer thread must not share a collective sequence with
    main-thread metric syncs: the session scopes its own whole-world
    subgroup, so syncs issued while snapshots are in flight stay
    correctly paired on every rank."""
    directory = str(tmp_path)
    world = ThreadWorld(2)
    per_rank = _per_rank_batches(2, 9, seed=23)

    def body(g):
        metrics = _fresh()
        session = ElasticSession(
            metrics,
            directory,
            process_group=g,
            interval=3,
            async_writer=True,
        )
        assert session._comm is not g  # dedicated communicator
        values = []
        for step in range(9):
            _feed(metrics, per_rank[g.rank][step])
            session.step_done(step)
            # a metric sync on the ORIGINAL group every step, while the
            # writer may be mid-snapshot on its own communicator
            values.append(
                float(np.asarray(sync_and_compute(metrics["acc"], g)))
            )
        session.close()
        return values

    results = world.run(body)
    assert results[0] == results[1]  # every sync paired correctly
    # and the bundles restore fine at the same world size
    def body_restore(g):
        metrics = _fresh()
        session = ElasticSession(metrics, directory, process_group=g)
        restored = session.restore()
        return restored.step

    assert ThreadWorld(2).run(body_restore) == [9, 9]


# ------------------------------------------------- world-size-change resume


def _per_rank_batches(world, steps, seed):
    rng = np.random.default_rng(seed)
    return [
        [
            (
                np.float32(rng.uniform(size=(8, 4))),
                rng.integers(0, 4, 8),
            )
            for _ in range(steps)
        ]
        for _ in range(world)
    ]


def _world_change(tmp_path, old_world, new_world):
    pre = _per_rank_batches(old_world, 6, seed=100 + old_world)
    post = _per_rank_batches(new_world, 4, seed=200 + new_world)
    directory = str(tmp_path)

    def body_old(g):
        metrics = _fresh()
        session = ElasticSession(
            metrics, directory, process_group=g, interval=3
        )
        for step in range(6):
            _feed(metrics, pre[g.rank][step])
            session.step_done(step)
        session.close()

    ThreadWorld(old_world).run(body_old)

    def body_new(g):
        metrics = _fresh()
        session = ElasticSession(
            metrics, directory, process_group=g, interval=3
        )
        restored = session.restore()
        for step in range(restored.step, restored.step + 4):
            _feed(metrics, post[g.rank][step - restored.step])
            session.step_done(step)
        session.close()
        synced = {
            name: get_synced_metric(m, g) for name, m in metrics.items()
        }
        return restored, _values(synced), synced["auroc"].num_samples

    results = ThreadWorld(new_world).run(body_new)

    # redistribute ORACLE, in-memory: old-rank metrics fed the pre-crash
    # stream, contiguously merged onto the new ranks exactly as restore()
    # does, then fed the post-resume stream and merged across new ranks —
    # the merge order an uninterrupted elastic run implies.
    old = [_fresh() for _ in range(old_world)]
    for rank in range(old_world):
        for step in range(6):
            _feed(old[rank], pre[rank][step])
    from torcheval_tpu.elastic import _assign_shards

    assignment = _assign_shards(old_world, new_world)
    new = []
    for rank in range(new_world):
        assigned = assignment[rank]
        metrics = _fresh()
        for name in metrics:
            peers = [clone_metric(old[r][name]) for r in assigned]
            if peers:
                metrics[name] = peers[0]
                metrics[name].merge_state(peers[1:])
        new.append(metrics)
    for rank in range(new_world):
        for step in range(4):
            _feed(new[rank], post[rank][step])
    merged = new[0]
    for name in merged:
        merged[name].merge_state([new[r][name] for r in range(1, new_world)])
    oracle = _values(merged)

    for rank in range(new_world):
        restored, values, num_samples = results[rank]
        assert restored.world_size == old_world
        assert restored.step == 6
        _assert_bit_identical(values, oracle)
    # every old rank's shard was assigned exactly once, in ascending order
    all_assigned = [r for res in results for r in res[0].assigned_ranks]
    assert all_assigned == list(range(old_world))
    # no sample lost or double-counted across the world change
    assert results[0][2] == old_world * 6 * 8 + new_world * 4 * 8


def test_world_shrink_4_to_2(tmp_path):
    _world_change(tmp_path, 4, 2)


def test_world_grow_2_to_4(tmp_path):
    _world_change(tmp_path, 2, 4)


# ------------------------------------------------- survivor re-formation


def _metric_for(rank):
    rng = np.random.default_rng(rank)
    m = MulticlassAccuracy()
    m.update(np.float32(rng.uniform(size=(16, 4))), rng.integers(0, 4, 16))
    return m


def test_reform_after_consecutive_degraded_syncs():
    """After ``reform_after`` consecutive quorum-degraded syncs missing
    the SAME rank, the group re-forms onto the survivors: subsequent
    syncs run undegraded with subgroup-relative provenance, the reform is
    visible in SyncHealth, and every provenance from the reform on is
    stamped ``reformed=True``."""
    world = ThreadWorld(4)

    def body(g):
        if g.rank == 3:
            # the dying host: present for the first two (degraded) syncs,
            # then gone — it never observes the reform
            for _ in range(2):
                get_synced_metric(_metric_for(g.rank), g)
            return None
        chaos = FaultInjectionGroup(g, dead_ranks={3})
        group = ResilientGroup(
            chaos, timeout=10.0, policy="quorum", reform_after=2
        )
        provs = []
        for _ in range(4):
            synced = get_synced_metric(_metric_for(g.rank), group)
            provs.append(synced.sync_provenance)
        return provs, group.health.as_dict(), group.ranks, float(
            np.asarray(synced.compute())
        )

    results = world.run(body)
    # the post-reform merged value: survivors 0..2, full participation
    oracle = _metric_for(0)
    oracle.merge_state([_metric_for(1), _metric_for(2)])
    want = float(np.asarray(oracle.compute()))
    for rank in range(3):
        provs, health, ranks, value = results[rank]
        # sync 0: degraded, pre-reform
        assert provs[0].degraded and provs[0].world_size == 4
        assert provs[0].ranks == (0, 1, 2) and not provs[0].reformed
        # sync 1: still the old world (the reform lands AFTER the sync
        # completes), but the reform event is stamped
        assert provs[1].degraded and provs[1].world_size == 4
        assert provs[1].reformed
        # syncs 2-3: survivors-only subgroup, undegraded, full speed
        for p in provs[2:]:
            assert not p.degraded
            assert p.world_size == 3 and p.ranks == (0, 1, 2)
            assert p.reformed
        assert health["reforms"] == 1
        assert health["reformed_to"] == [0, 1, 2]
        assert health["degraded_syncs"] == 2
        assert health["full_syncs"] == 2
        assert ranks == (0, 1, 2)  # the active group is the subgroup
        assert value == want


def test_reform_requires_same_missing_ranks():
    """Two degraded syncs missing DIFFERENT ranks must not escalate —
    only a PERSISTENT failure re-forms the group."""
    world = ThreadWorld(3)

    def body(g):
        chaos = FaultInjectionGroup(g)
        group = ResilientGroup(
            chaos, timeout=10.0, policy="quorum", reform_after=2
        )
        from torcheval_tpu.utils.test_utils import FaultSpec

        # sync 0 loses rank 1 (both collectives), sync 1 loses rank 2
        chaos.faults.extend(
            [
                FaultSpec(call=0, kind="drop", rank=1, times=2),
                FaultSpec(call=2, kind="drop", rank=2, times=2),
            ]
        )
        provs = []
        for _ in range(2):
            synced = get_synced_metric(_metric_for(g.rank), group)
            provs.append(synced.sync_provenance)
        return provs, group.health.as_dict()

    results = world.run(body)
    for provs, health in results:
        assert all(not p.reformed for p in provs)
        assert health["reforms"] == 0
        assert health["consecutive_missing_count"] <= 1  # streak reset
    # rank 0 observed both losses (it was never the dropped rank itself):
    # two degraded syncs, different survivors, no escalation
    provs0, _ = results[0]
    assert provs0[0].ranks != provs0[1].ranks
    assert all(p.degraded for p in provs0)


def test_reform_composes_with_elastic_resume(tmp_path):
    """The full elastic story: a rank dies, the survivors re-form and
    keep snapshotting on the smaller world; a replacement job restores
    those bundles at the new world size."""
    directory = str(tmp_path)
    world = ThreadWorld(4)
    pre = _per_rank_batches(4, 4, seed=42)

    def body(g):
        metrics = _fresh()
        if g.rank == 3:
            # dies before contributing anything durable: participates in
            # the two degraded syncs, writes no snapshot
            for _ in range(2):
                get_synced_metric({"acc": _metric_for(g.rank)}["acc"], g)
            return None
        chaos = FaultInjectionGroup(g, dead_ranks={3})
        group = ResilientGroup(
            chaos, timeout=10.0, policy="quorum", reform_after=2
        )
        for _ in range(2):  # ride out the dead rank; triggers the reform
            get_synced_metric(_metric_for(g.rank), group)
        assert group.world_size == 3
        # survivors snapshot on the REFORMED world: rank identities and
        # world size come from the reformed group
        session = ElasticSession(
            metrics, directory, process_group=group, interval=2
        )
        for step in range(4):
            _feed(metrics, pre[g.rank][step])
            session.step_done(step)
        session.close()
        return sync_and_compute(metrics["acc"], group)

    world.run(body)

    # a replacement 2-rank job restores the 3-survivor bundles
    def body_new(g):
        metrics = _fresh()
        session = ElasticSession(metrics, directory, process_group=g)
        restored = session.restore()
        synced = get_synced_metric(metrics["acc"], g)
        return restored, np.asarray(synced.compute())

    results = ThreadWorld(2).run(body_new)
    oracle = _fresh()
    for rank in range(3):
        for step in range(4):
            _feed(oracle, pre[rank][step])
    for restored, value in results:
        assert restored.world_size == 3 and restored.step == 4
        assert np.array_equal(value, np.asarray(oracle["acc"].compute()))


# ------------------------------------------------------ provenance hygiene


def test_reset_clears_stale_sync_provenance():
    """Satellite regression: ``Metric.reset()`` (and a state restore)
    must drop the provenance a prior degraded sync attached — stale
    ``degraded=True`` on a reset metric misreports fresh state."""
    from torcheval_tpu.resilience import SyncProvenance

    m = _metric_for(0)
    m.sync_provenance = SyncProvenance(
        ranks=(0,), world_size=4, degraded=True, policy="quorum"
    )
    m.reset()
    assert not hasattr(m, "sync_provenance")

    m = _metric_for(0)
    m.sync_provenance = SyncProvenance(
        ranks=(0,), world_size=4, degraded=True, policy="quorum"
    )
    m.load_state_dict(_metric_for(1).state_dict())
    assert not hasattr(m, "sync_provenance")


def test_checkpoint_restore_clears_stale_sync_provenance(tmp_path):
    from torcheval_tpu.resilience import SyncProvenance
    from torcheval_tpu.utils import load_metric_state, save_metric_state

    m = _metric_for(0)
    save_metric_state(m, str(tmp_path / "ck"))
    target = _metric_for(1)
    target.sync_provenance = SyncProvenance(
        ranks=(0,), world_size=4, degraded=True, policy="quorum"
    )
    load_metric_state(target, str(tmp_path / "ck"))
    assert not hasattr(target, "sync_provenance")


# ------------------------------------------- sharded-state elastic resume


def _sharded_world_change(tmp_path, old_world, new_world):
    """ISSUE 9 satellite: a SHARDED confusion matrix's per-rank shards
    (+ routed outboxes) ARE the on-disk snapshot layout; a world-size
    change restore must reassemble the logical state from every old
    rank's shard and outbox and re-slice it to the new world —
    bit-identical to the uninterrupted replicated oracle, with no
    contribution lost or double-counted."""
    from torcheval_tpu.metrics import MulticlassConfusionMatrix, ShardContext

    C = 8
    rng = np.random.default_rng(700 + old_world * 10 + new_world)
    pre = [
        [
            (rng.integers(0, C, 16), rng.integers(0, C, 16))
            for _ in range(6)
        ]
        for _ in range(old_world)
    ]
    post = [
        [
            (rng.integers(0, C, 16), rng.integers(0, C, 16))
            for _ in range(4)
        ]
        for _ in range(new_world)
    ]
    directory = str(tmp_path)

    def body_old(g):
        metrics = {
            "cm": MulticlassConfusionMatrix(
                C, shard=ShardContext(g.rank, old_world)
            )
        }
        session = ElasticSession(
            metrics, directory, process_group=g, interval=3
        )
        for step in range(6):
            metrics["cm"].update(*pre[g.rank][step])
            session.step_done(step)
        session.close()

    ThreadWorld(old_world).run(body_old)

    def body_new(g):
        metrics = {
            "cm": MulticlassConfusionMatrix(
                C, shard=ShardContext(g.rank, new_world)
            )
        }
        session = ElasticSession(
            metrics, directory, process_group=g, interval=3
        )
        restored = session.restore()
        # the live metric is back on its OWN new-world shard
        assert metrics["cm"].confusion_matrix.shape == (C // new_world, C)
        assert metrics["cm"]._shard_rank == g.rank
        assert metrics["cm"]._shard_world == new_world
        for step in range(restored.step, restored.step + 4):
            metrics["cm"].update(*post[g.rank][step - restored.step])
            session.step_done(step)
        session.close()
        return restored.step, np.asarray(sync_and_compute(metrics["cm"], g))

    results = ThreadWorld(new_world).run(body_new)
    restored_step = results[0][0]
    assert restored_step == 6

    # uninterrupted REPLICATED oracle: all pre-crash batches (every old
    # rank, snapshot-covered steps) plus all post-resume batches
    oracle = MulticlassConfusionMatrix(C)
    for rank in range(old_world):
        for step in range(restored_step):
            oracle.update(*pre[rank][step])
    for rank in range(new_world):
        for step in range(4):
            oracle.update(*post[rank][step])
    expected = np.asarray(oracle.compute())
    for _, value in results:
        np.testing.assert_array_equal(value, expected)


def test_sharded_confusion_matrix_resumes_4_to_2(tmp_path):
    _sharded_world_change(tmp_path, 4, 2)


def test_sharded_confusion_matrix_resumes_2_to_4(tmp_path):
    _sharded_world_change(tmp_path, 2, 4)


def test_sharded_confusion_matrix_resumes_same_world(tmp_path):
    """Same-world restore stays on the fast path: each rank loads its
    own self-describing shard directly (no logical materialization),
    outbox entries included."""
    _sharded_world_change(tmp_path, 4, 4)


def test_sharded_confusion_matrix_resumes_1_to_2(tmp_path):
    """World-1 sharded metrics route nothing at update (their outboxes
    stay empty); scaling OUT from such a snapshot re-slices the full
    shard onto the routed new-world instances."""
    _sharded_world_change(tmp_path, 1, 2)


def test_sharded_confusion_matrix_resumes_2_to_1(tmp_path):
    """Scale-IN to world 1: the lone new rank merges every old shard AND
    every old rank's outbox (foreign contributions must not drop) and
    re-slices to the full logical state."""
    _sharded_world_change(tmp_path, 2, 1)
