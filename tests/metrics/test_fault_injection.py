"""Deterministic fault-injection suite for the resilient sync path.

Proves every ``ResilientGroup`` degradation policy does what it claims
(ISSUE 2 acceptance):

- with one injected dead rank, ``sync_and_compute`` under ``quorum``
  returns within the configured deadline with the surviving ranks' merged
  value and a populated ``SyncHealth``;
- under ``raise`` it raises a typed ``SyncTimeoutError`` instead of
  hanging;
- the happy path adds ZERO extra collectives (also pinned from the
  collective-count side by ``test_sync_collective_counts.py``);
- the quorum merge is a deterministic function of the surviving-rank
  subset alone: the same survivors produce bit-identical merged state no
  matter WHICH collective attempt lost the rank.

All faults are scripted through ``utils.test_utils.FaultInjectionGroup``
(seeded, call-indexed — no wall-clock nondeterminism decides what fails).
"""

from __future__ import annotations

import copy
import time

import numpy as np
import pytest


@pytest.fixture(autouse=True)
def _dump_events_on_failure(obs_recorder):
    """Flake forensics: run this whole suite with the observability
    recorder on, so a failure report carries the event-log tail (every
    retry, degradation, and sync provenance the test produced — the
    conftest ``pytest_runtest_makereport`` hook appends it)."""
    yield

import jax

from tests.metrics._sync_matrix import build_rank_replicas
from torcheval_tpu import config
from torcheval_tpu.distributed import LocalReplicaGroup, ProcessGroup
from torcheval_tpu.metrics import MulticlassAccuracy
from torcheval_tpu.metrics.toolkit import (
    get_synced_metric,
    sync_and_compute,
)
from torcheval_tpu.resilience import (
    PartialGatherError,
    ResilientGroup,
    SyncIntegrityError,
    SyncTimeoutError,
)
from torcheval_tpu.utils.test_utils import FaultInjectionGroup, FaultSpec

WORLD = 3


@pytest.fixture(autouse=True)
def _drain_abandoned_collectives():
    """The in-flight fence is process-global (by design — it must survive
    group objects): drain this test's abandoned stragglers so they cannot
    fence the NEXT test's collectives."""
    yield
    from torcheval_tpu import resilience

    assert not resilience._still_in_flight(5.0), (
        "an abandoned collective outlived its test"
    )


def _local_group(world: int = WORLD) -> LocalReplicaGroup:
    devices = jax.local_devices()
    assert len(devices) >= world, "conftest provides 8 virtual CPU devices"
    return LocalReplicaGroup(devices[:world])


def _replicas(name: str = "MulticlassAccuracy", world: int = WORLD):
    return build_rank_replicas(name, world)


def _merge_oracle(replicas, ranks):
    """Reference merge of the given surviving ranks, no wire involved."""
    survivors = [copy.deepcopy(replicas[r]) for r in ranks]
    return survivors[0].merge_state(survivors[1:])


# --------------------------------------------------------------- happy path


class _CountingGroup(ProcessGroup):
    """Two fake ranks, both holding this process's payload; counts calls."""

    def __init__(self):
        self.object_gathers = 0
        self.array_gathers = 0

    @property
    def world_size(self):
        return 2

    @property
    def rank(self):
        return 0

    def allgather_object(self, obj):
        self.object_gathers += 1
        return [obj, copy.deepcopy(obj)]

    def allgather_array(self, x):
        self.array_gathers += 1
        x = np.asarray(x)
        return [x, x.copy()]


def test_happy_path_zero_extra_collectives_and_same_value():
    metric = MulticlassAccuracy()
    metric.update(
        np.float32(np.random.default_rng(0).uniform(size=(8, 4))),
        np.random.default_rng(1).integers(0, 4, size=8),
    )

    plain = _CountingGroup()
    want = sync_and_compute(copy.deepcopy(metric), plain)

    counting = _CountingGroup()
    group = ResilientGroup(counting, timeout=5.0, retries=2, policy="quorum")
    got = sync_and_compute(copy.deepcopy(metric), group)

    # identical collective budget at the ProcessGroup interface
    assert counting.object_gathers == plain.object_gathers == 1
    assert counting.array_gathers == plain.array_gathers <= 1
    np.testing.assert_allclose(np.asarray(got), np.asarray(want))
    # health: fully participating, nothing degraded
    assert group.health.full_syncs == 1
    assert group.health.degraded_syncs == 0
    assert group.health.participating_ranks == (0, 1)
    assert group.health.last_good_sync is not None


def test_happy_path_local_replicas_unchanged_by_wrapping():
    replicas = _replicas()
    want = sync_and_compute([copy.deepcopy(m) for m in replicas], _local_group())
    group = ResilientGroup(
        FaultInjectionGroup(_local_group()),  # no faults scripted
        timeout=5.0,
        policy="quorum",
    )
    got = sync_and_compute([copy.deepcopy(m) for m in replicas], group)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want))
    assert group.health.participating_ranks == tuple(range(WORLD))


# ------------------------------------------------------------ dead rank


def test_quorum_merges_surviving_ranks_within_deadline():
    replicas = _replicas()
    chaos = FaultInjectionGroup(_local_group(), dead_ranks={1})
    group = ResilientGroup(
        chaos, timeout=2.0, retries=1, policy="quorum", backoff_base=0.0
    )
    start = time.monotonic()
    synced = get_synced_metric([copy.deepcopy(m) for m in replicas], group)
    elapsed = time.monotonic() - start
    assert elapsed < 10.0, "degradation must be bounded, not a hang"

    want = _merge_oracle(replicas, [0, 2]).compute()
    np.testing.assert_allclose(
        np.asarray(synced.compute()), np.asarray(want)
    )
    # provenance names exactly the contributing ranks
    assert synced.sync_provenance.ranks == (0, 2)
    assert synced.sync_provenance.degraded
    assert synced.sync_provenance.policy == "quorum"
    # health populated
    assert group.health.partial_gathers >= 1
    assert group.health.degraded_syncs == 1
    assert group.health.participating_ranks == (0, 2)
    assert group.health.last_good_sync is None  # never a full sync


def test_raise_policy_dead_rank_is_typed_not_a_hang():
    replicas = _replicas()
    chaos = FaultInjectionGroup(_local_group(), dead_ranks={1})
    group = ResilientGroup(
        chaos, timeout=2.0, retries=1, policy="raise", backoff_base=0.0
    )
    start = time.monotonic()
    with pytest.raises(SyncTimeoutError):
        sync_and_compute([copy.deepcopy(m) for m in replicas], group)
    assert time.monotonic() - start < 10.0


def test_raise_policy_slow_peer_times_out_typed():
    replicas = _replicas()
    chaos = FaultInjectionGroup(
        _local_group(),
        faults=[FaultSpec(call=0, kind="delay", seconds=0.5, times=99)],
    )
    group = ResilientGroup(
        chaos, timeout=0.05, retries=1, policy="raise", backoff_base=0.0
    )
    start = time.monotonic()
    with pytest.raises(SyncTimeoutError):
        sync_and_compute([copy.deepcopy(m) for m in replicas], group)
    assert time.monotonic() - start < 5.0
    assert group.health.timeouts == 2  # first attempt + one retry
    # a timed-out collective is NEVER reissued while still in flight
    # (reissuing would desynchronize the rank-wide collective order):
    # the retry extended the wait on the ONE issued collective
    assert chaos.calls == 1


def test_late_completion_harvested_instead_of_reissued():
    """A collective that misses the deadline but completes during the
    retry wait is harvested — full participation, exactly one collective
    issued per exchange."""
    replicas = _replicas()
    chaos = FaultInjectionGroup(
        _local_group(),
        # both collectives of the sync run slow, but finish well inside
        # the retry's extended wait (backoff + another deadline)
        faults=[FaultSpec(call=0, kind="delay", seconds=0.15, times=2)],
    )
    group = ResilientGroup(
        chaos, timeout=0.05, retries=2, policy="raise", backoff_base=0.1
    )
    want = sync_and_compute(
        [copy.deepcopy(m) for m in replicas], _local_group()
    )
    got = sync_and_compute([copy.deepcopy(m) for m in replicas], group)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want))
    assert group.health.timeouts >= 1
    assert chaos.calls == 2  # one per exchange, no reissue


def test_auto_wrapped_syncs_do_not_leak_worker_threads():
    """Config-driven wrapping builds a fresh ResilientGroup per toolkit
    call; the deadline worker is process-shared, so repeated syncs must
    not accumulate threads."""
    import threading

    def worker_count():
        return sum(
            t.name.startswith("torcheval-sync") for t in threading.enumerate()
        )

    replicas = _replicas()
    before = worker_count()  # stragglers poisoned by earlier delay tests
    with config.sync_resilience(timeout=5.0, degradation="quorum"):
        for _ in range(25):
            sync_and_compute(
                [copy.deepcopy(m) for m in replicas], _local_group()
            )
    assert worker_count() - before <= 1, (
        f"worker threads leaked: {before} -> {worker_count()}"
    )


def test_in_flight_collective_fences_the_next_one():
    """After a collective is abandoned mid-flight, NO new collective is
    issued on that group until the stuck one completes — issuing would
    desynchronize the rank-wide collective order. The fenced collective
    degrades bounded; once the straggler lands, syncs resume in full."""
    replicas = _replicas()
    chaos = FaultInjectionGroup(
        _local_group(),
        faults=[FaultSpec(call=0, kind="delay", seconds=0.6, times=1)],
    )
    group = ResilientGroup(
        chaos, timeout=0.05, retries=0, policy="local", backoff_base=0.0
    )
    synced = get_synced_metric([copy.deepcopy(m) for m in replicas], group)
    assert synced.sync_provenance.ranks == (0,)
    # the payload gather was FENCED, never issued, while the metadata
    # gather was still in flight on its abandoned worker
    assert chaos.calls == 1
    time.sleep(0.7)  # let the straggler land
    synced2 = get_synced_metric([copy.deepcopy(m) for m in replicas], group)
    assert synced2.sync_provenance.ranks == tuple(range(WORLD))
    assert chaos.calls == 3  # both collectives of the second sync issued


def test_timed_out_worker_threads_are_daemons():
    """Abandoned workers stuck in a hung collective must not block
    interpreter exit (they are daemon threads, and nothing registers an
    atexit join over them)."""
    import threading

    chaos = FaultInjectionGroup(
        _local_group(),
        faults=[FaultSpec(call=0, kind="delay", seconds=0.3, times=1)],
    )
    group = ResilientGroup(
        chaos, timeout=0.02, retries=0, policy="local", backoff_base=0.0
    )
    # times out, degrades to local-only participation
    _, ranks = group.allgather_object_with_ranks(["a", "b", "c"])
    assert ranks == [0]
    stuck = [
        t for t in threading.enumerate() if t.name.startswith("torcheval-sync")
    ]
    assert stuck, "worker thread should exist"
    assert all(t.daemon for t in stuck)


def test_local_policy_falls_back_to_own_state_flagged_stale():
    replicas = _replicas()
    chaos = FaultInjectionGroup(_local_group(), dead_ranks={1})
    group = ResilientGroup(
        chaos, timeout=2.0, retries=0, policy="local", backoff_base=0.0
    )
    synced = get_synced_metric([copy.deepcopy(m) for m in replicas], group)
    np.testing.assert_allclose(
        np.asarray(synced.compute()),
        np.asarray(copy.deepcopy(replicas[0]).compute()),
    )
    assert synced.sync_provenance.ranks == (0,)
    assert synced.sync_provenance.degraded


def test_quorum_not_met_raises():
    replicas = _replicas("MulticlassAccuracy", 4)
    chaos = FaultInjectionGroup(_local_group(4), dead_ranks={1, 2, 3})
    group = ResilientGroup(
        chaos, timeout=2.0, retries=0, policy="quorum", quorum=0.75,
        backoff_base=0.0,
    )
    with pytest.raises(SyncTimeoutError, match="quorum"):
        sync_and_compute([copy.deepcopy(m) for m in replicas], group)


# ------------------------------------------------------- transient + retry


def test_transient_fault_is_retried_to_full_participation():
    replicas = _replicas()
    chaos = FaultInjectionGroup(
        _local_group(),
        faults=[FaultSpec(call=0, kind="transient", times=1)],
    )
    group = ResilientGroup(
        chaos, timeout=5.0, retries=2, policy="raise", backoff_base=0.0
    )
    want = sync_and_compute([copy.deepcopy(m) for m in replicas], _local_group())
    got = sync_and_compute([copy.deepcopy(m) for m in replicas], group)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want))
    assert group.health.transient_errors == 1
    assert group.health.retries == 1
    assert group.health.full_syncs == 1
    assert group.health.participating_ranks == tuple(range(WORLD))


def test_transient_drop_recovers_on_retry():
    """A drop that clears after one attempt (times=1) costs a retry, not a
    degradation — full participation is restored."""
    replicas = _replicas()
    chaos = FaultInjectionGroup(
        _local_group(),
        faults=[FaultSpec(call=0, kind="drop", rank=2, times=1)],
    )
    group = ResilientGroup(
        chaos, timeout=5.0, retries=2, policy="raise", backoff_base=0.0
    )
    synced = get_synced_metric([copy.deepcopy(m) for m in replicas], group)
    assert synced.sync_provenance.ranks == tuple(range(WORLD))
    assert not synced.sync_provenance.degraded
    assert group.health.partial_gathers == 1


# ------------------------------------------------------------- corruption


def test_corrupt_payload_dropped_under_quorum():
    replicas = _replicas()
    # call 0 is the metadata object gather, call 1 the byte payload gather
    chaos = FaultInjectionGroup(
        _local_group(),
        faults=[FaultSpec(call=1, kind="corrupt", rank=1)],
    )
    group = ResilientGroup(
        chaos, timeout=5.0, retries=0, policy="quorum", backoff_base=0.0
    )
    synced = get_synced_metric([copy.deepcopy(m) for m in replicas], group)
    assert synced.sync_provenance.ranks == (0, 2)
    want = _merge_oracle(replicas, [0, 2]).compute()
    np.testing.assert_allclose(np.asarray(synced.compute()), np.asarray(want))
    assert group.health.corrupt_payloads == 1


def test_corrupt_payload_raises_under_raise_policy():
    replicas = _replicas()
    chaos = FaultInjectionGroup(
        _local_group(),
        faults=[FaultSpec(call=1, kind="corrupt", rank=1)],
    )
    group = ResilientGroup(
        chaos, timeout=5.0, retries=0, policy="raise", backoff_base=0.0
    )
    with pytest.raises(SyncIntegrityError, match="checksum"):
        sync_and_compute([copy.deepcopy(m) for m in replicas], group)


def test_duplicate_payload_is_observable():
    """The duplicate fault swaps rank 1's payload for rank 0's: the merge
    then double-counts rank 0 — proving the harness really rewires the
    payload path (and that crc+size metadata travels WITH the payload, so
    a consistent duplicate is indistinguishable from the real thing, as on
    a real wire)."""
    replicas = _replicas()
    chaos = FaultInjectionGroup(
        _local_group(),
        faults=[
            FaultSpec(call=0, kind="duplicate", rank=1, src=0),
            FaultSpec(call=1, kind="duplicate", rank=1, src=0),
        ],
    )
    group = ResilientGroup(chaos, timeout=5.0, retries=0, policy="raise")
    got = sync_and_compute([copy.deepcopy(m) for m in replicas], group)
    doubled = _merge_oracle(replicas, [0, 0, 2]).compute()
    np.testing.assert_allclose(np.asarray(got), np.asarray(doubled))


# ------------------------------------------------- determinism guarantees


@pytest.mark.parametrize(
    "case_name",
    ["MulticlassAccuracy", "Sum", "BinaryAUROC", "WindowedMeanSquaredError"],
)
def test_quorum_merge_deterministic_across_failing_collective(case_name):
    """Same surviving-rank subset -> bit-identical merged state, no matter
    which collective attempt lost the rank (metadata vs payload gather)."""

    def _synced_state(fault_call):
        replicas = _replicas(case_name)
        chaos = FaultInjectionGroup(
            _local_group(),
            faults=[FaultSpec(call=fault_call, kind="drop", rank=1)],
        )
        group = ResilientGroup(
            chaos, timeout=5.0, retries=0, policy="quorum", backoff_base=0.0
        )
        synced = get_synced_metric(
            [copy.deepcopy(m) for m in replicas], group
        )
        assert synced.sync_provenance.ranks == (0, 2)
        return synced.state_dict()

    state_meta_lost = _synced_state(0)  # metadata gather lost rank 1
    state_payload_lost = _synced_state(1)  # payload gather lost rank 1

    assert state_meta_lost.keys() == state_payload_lost.keys()
    flat_a = jax.tree_util.tree_leaves(state_meta_lost)
    flat_b = jax.tree_util.tree_leaves(state_payload_lost)
    assert len(flat_a) == len(flat_b)
    for a, b in zip(flat_a, flat_b):
        a, b = np.asarray(a), np.asarray(b)
        assert a.dtype == b.dtype and a.shape == b.shape
        np.testing.assert_array_equal(a, b)  # bit-identical


def test_backoff_schedule_is_seed_deterministic():
    mk = lambda seed: ResilientGroup(
        _local_group(), policy="quorum", seed=seed,
        backoff_base=0.01, backoff_max=0.08, backoff_jitter=0.5,
    )
    a, b, c = mk(7), mk(7), mk(8)
    sched_a = [a._next_backoff(k) for k in range(1, 6)]
    sched_b = [b._next_backoff(k) for k in range(1, 6)]
    sched_c = [c._next_backoff(k) for k in range(1, 6)]
    assert sched_a == sched_b
    assert sched_a != sched_c
    for k, delay in enumerate(sched_a, start=1):
        base = min(0.01 * 2 ** (k - 1), 0.08)
        assert base <= delay <= base * 1.5  # jitter in [0, 0.5]


def test_fault_injection_group_is_deterministic_replay():
    """Two identical chaos+resilience stacks over identical replicas give
    identical provenance, health counters, and merged value."""

    def run():
        replicas = _replicas()
        chaos = FaultInjectionGroup(
            _local_group(),
            faults=[FaultSpec(call=0, kind="transient", times=1)],
            dead_ranks={2},
            seed=3,
        )
        group = ResilientGroup(
            chaos, timeout=5.0, retries=2, policy="quorum", backoff_base=0.0,
            seed=3,
        )
        synced = get_synced_metric(
            [copy.deepcopy(m) for m in replicas], group
        )
        return (
            np.asarray(synced.compute()),
            synced.sync_provenance,
            group.health.as_dict(),
            chaos.calls,
        )

    value_a, prov_a, health_a, calls_a = run()
    value_b, prov_b, health_b, calls_b = run()
    np.testing.assert_array_equal(value_a, value_b)
    assert prov_a == prov_b
    health_a.pop("last_good_sync"), health_b.pop("last_good_sync")
    assert health_a == health_b
    assert calls_a == calls_b


# ----------------------------------------------------------- misc contracts


def test_partial_gather_propagates_without_resilience():
    """The chaos wrapper alone (no ResilientGroup) surfaces peer loss as
    the typed PartialGatherError carrying the survivors' payloads."""
    chaos = FaultInjectionGroup(_local_group(), dead_ranks={1})
    with pytest.raises(PartialGatherError) as err:
        chaos.allgather_object(["a", "b", "c"])
    assert sorted(err.value.values) == [0, 2]
    assert err.value.values[2] == "c"


def test_on_failure_overrides_policy_per_call():
    replicas = _replicas()
    chaos = FaultInjectionGroup(_local_group(), dead_ranks={1})
    group = ResilientGroup(
        chaos, timeout=2.0, retries=0, policy="raise", backoff_base=0.0
    )
    with pytest.raises(SyncTimeoutError):
        sync_and_compute([copy.deepcopy(m) for m in replicas], group)
    # same group, per-call quorum override; health is shared
    synced = get_synced_metric(
        [copy.deepcopy(m) for m in replicas], group, on_failure="quorum"
    )
    assert synced.sync_provenance.ranks == (0, 2)
    assert group.health.degraded_syncs == 1


def test_config_knobs_wrap_default_path(monkeypatch):
    """A configured degradation policy wraps plain groups automatically —
    callers keep the reference API and still get bounded failure."""
    replicas = _replicas()
    chaos = FaultInjectionGroup(_local_group(), dead_ranks={1})
    with config.sync_resilience(timeout=2.0, retries=0, degradation="quorum"):
        synced = get_synced_metric([copy.deepcopy(m) for m in replicas], chaos)
    assert synced.sync_provenance.ranks == (0, 2)
    want = _merge_oracle(replicas, [0, 2]).compute()
    np.testing.assert_allclose(np.asarray(synced.compute()), np.asarray(want))


def test_resilient_group_rejects_bad_policy_and_quorum():
    with pytest.raises(ValueError, match="policy"):
        ResilientGroup(_local_group(), policy="retry-forever")
    with pytest.raises(ValueError, match="quorum"):
        ResilientGroup(_local_group(), quorum=0.0)


def test_zero_timeout_rejected_everywhere():
    """timeout=0 would silently DISABLE the deadline (run-inline path) —
    the un-bounded hang the knob exists to prevent; it must be rejected,
    not accepted with inverted semantics."""
    for bad in (0.0, -1.0, float("nan")):
        with pytest.raises(ValueError, match="positive finite"):
            ResilientGroup(_local_group(), timeout=bad)
        with pytest.raises(ValueError, match="positive finite"):
            config.set_sync_timeout(bad)


def test_plain_allgather_refuses_partial_results():
    """The inherited allgather contract is one payload per rank IN RANK
    ORDER; after degradation the plain entry points raise instead of
    silently mis-attributing ranks (rank-aware callers use _with_ranks)."""
    chaos = FaultInjectionGroup(_local_group(), dead_ranks={1})
    group = ResilientGroup(
        chaos, timeout=2.0, retries=0, policy="quorum", backoff_base=0.0
    )
    with pytest.raises(SyncTimeoutError, match="with_ranks"):
        group.allgather_object(["a", "b", "c"])
    values, ranks = group.allgather_object_with_ranks(["a", "b", "c"])
    assert ranks == [0, 2] and values == ["a", "c"]


def test_world_of_one_carries_full_provenance():
    """The world_size==1 fast path must honor the documented provenance
    surface (code branching on .sync_provenance.degraded must not crash
    in the smallest deployment)."""
    from torcheval_tpu.distributed import SingleProcessGroup

    m = _replicas(world=1)[0]
    synced = get_synced_metric(m, SingleProcessGroup())
    assert synced.sync_provenance.ranks == (0,)
    assert synced.sync_provenance.world_size == 1
    assert not synced.sync_provenance.degraded


def test_sync_resilience_context_does_not_leak_on_bad_knob():
    """A validation error on a later knob must not leak earlier knobs
    past the context."""
    before = config.sync_timeout()
    with pytest.raises(ValueError, match="policy"):
        with config.sync_resilience(timeout=99.0, degradation="quorom"):
            pass  # never entered
    assert config.sync_timeout() == before


def test_with_policy_keeps_shared_health_policy():
    """A per-call on_failure override shares the group's SyncHealth but
    must not rewrite its reported policy."""
    group = ResilientGroup(_local_group(), policy="raise")
    sibling = group.with_policy("local")
    assert sibling.health is group.health
    assert sibling.policy == "local"
    assert group.health.policy == "raise"  # the creator's, unclobbered


def test_degrading_policy_arms_default_deadline():
    """A degrading policy without an explicit timeout must still bound a
    dead-host wait: on a plain group degradation only fires on timeout,
    so policy != raise arms DEFAULT_DEGRADING_TIMEOUT automatically."""
    from torcheval_tpu.resilience import DEFAULT_DEGRADING_TIMEOUT

    group = ResilientGroup(_local_group(), policy="quorum")  # no timeout
    assert group.timeout == DEFAULT_DEGRADING_TIMEOUT
    # raise policy keeps the reference wait-forever default
    assert ResilientGroup(_local_group(), policy="raise").timeout is None


def test_late_completion_reclaims_worker_thread():
    """A deadline miss whose collective lands LATE must not leak its
    worker: the thread is reinstated (or stopped) once the straggler
    completes, so repeated slow-but-completing syncs stay at one worker."""
    import threading

    from torcheval_tpu import resilience

    def worker_count():
        return sum(
            t.name.startswith("torcheval-sync") and t.is_alive()
            for t in threading.enumerate()
        )

    replicas = _replicas()
    assert not resilience._still_in_flight(5.0)  # drain prior stragglers
    before = worker_count()
    for _ in range(3):  # each sync: miss deadline, harvest late
        chaos = FaultInjectionGroup(
            _local_group(),
            faults=[FaultSpec(call=0, kind="delay", seconds=0.15, times=2)],
        )
        group = ResilientGroup(
            chaos, timeout=0.05, retries=2, policy="raise", backoff_base=0.1
        )
        sync_and_compute([copy.deepcopy(m) for m in replicas], group)
    assert not resilience._still_in_flight(5.0)
    time.sleep(0.1)  # stopped surplus workers exit their loops
    assert worker_count() - before <= 1, "late-completion workers leaked"


def test_config_driven_health_reports_effective_policy():
    """default_sync_health() must report the policy actually in effect
    for config-driven syncs, not its construction-time default."""
    from torcheval_tpu.resilience import default_sync_health

    replicas = _replicas()
    with config.sync_resilience(timeout=5.0, degradation="quorum"):
        sync_and_compute([copy.deepcopy(m) for m in replicas], _local_group())
    assert default_sync_health().policy == "quorum"


def test_config_driven_syncs_accumulate_default_health():
    """Auto-wrapped groups live one call each; their counters must land in
    the process-wide default_sync_health() or the documented observability
    surface is unreachable in config-driven mode."""
    from torcheval_tpu.resilience import default_sync_health

    replicas = _replicas()
    before = default_sync_health().attempts
    with config.sync_resilience(timeout=5.0, degradation="quorum"):
        for _ in range(3):
            sync_and_compute(
                [copy.deepcopy(m) for m in replicas], _local_group()
            )
    grew = default_sync_health().attempts - before
    assert grew >= 6  # >= 2 collectives per sync, 3 syncs, accumulated


def test_retries_env_knob_alone_triggers_wrapping():
    """Setting only sync_retries still routes syncs through a
    ResilientGroup (the knob must not be silently inert)."""
    with config.sync_resilience(retries=5):
        assert config.sync_resilience_configured()
    assert not config.sync_resilience_configured()
