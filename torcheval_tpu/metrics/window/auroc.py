"""WindowedBinaryAUROC.

Parity: reference torcheval/metrics/window/auroc.py:23-238. Unlike the other
windowed metrics this windows over *samples*: raw (input, target, weight)
triples live in fixed-shape (num_tasks, max_num_samples) ring buffers — the
XLA-friendly formulation of the reference's example-buffer AUROC. Vectorized
inserts follow the reference's three cases (oversized batch / fits in rest /
wraps, reference :109-154); merge packs valid prefixes of all replicas
(reference :181-238).
"""

from __future__ import annotations

from typing import Iterable, Optional, TypeVar

import jax
import jax.numpy as jnp

from torcheval_tpu.utils.convert import cached_index, default_ones

from torcheval_tpu.metrics.functional.classification.auroc import (
    _binary_auroc_compute,
    _binary_auroc_update_input_check,
)
from torcheval_tpu.metrics.metric import MergeKind, Metric
from torcheval_tpu.metrics.window._base import RingCursorSerializationMixin

TWindowedBinaryAUROC = TypeVar("TWindowedBinaryAUROC", bound="WindowedBinaryAUROC")



@jax.jit
def _ring_write_cols(buf: jax.Array, col: jax.Array, value: jax.Array) -> jax.Array:
    return jax.lax.dynamic_update_slice(buf, value.astype(buf.dtype), (jnp.int32(0), col))


class WindowedBinaryAUROC(RingCursorSerializationMixin, Metric[jax.Array]):
    """AUROC over the last ``max_num_samples`` samples.

    Examples::

        >>> from torcheval_tpu.metrics import WindowedBinaryAUROC
        >>> metric = WindowedBinaryAUROC(max_num_samples=4)
        >>> metric.update(jnp.array([0.2, 0.5, 0.1, 0.5, 0.7, 0.8]),
        ...               jnp.array([0, 1, 1, 0, 1, 1]))
        >>> metric.compute()
        Array(0.6666667, dtype=float32)
    """

    _cursor_total_state = "total_samples"
    _cursor_capacity_state = "max_num_samples"

    def __init__(
        self,
        *,
        num_tasks: int = 1,
        max_num_samples: int = 100,
        device: Optional[jax.Device] = None,
    ) -> None:
        super().__init__(device=device)
        if num_tasks < 1:
            raise ValueError(
                "`num_tasks` value should be greater than and equal to 1, "
                f"but received {num_tasks}. "
            )
        if max_num_samples < 1:
            raise ValueError(
                "`max_num_samples` value should be greater than and equal to "
                f"1, but received {max_num_samples}. "
            )
        self.num_tasks = num_tasks
        self._add_state("max_num_samples", max_num_samples, merge=MergeKind.CUSTOM)
        self.next_inserted = 0
        self._add_state("total_samples", 0, merge=MergeKind.CUSTOM)
        zeros = jnp.zeros((num_tasks, max_num_samples))
        self._add_state("inputs", zeros, merge=MergeKind.CUSTOM)
        self._add_state("targets", zeros, merge=MergeKind.CUSTOM)
        self._add_state("weights", zeros, merge=MergeKind.CUSTOM)

    def _write(self, name: str, col: int, value: jax.Array) -> None:
        # traced start column (cached device scalar): an eager .at slice-set
        # would compile per ring offset and upload constants per call
        buf = getattr(self, name)
        setattr(
            self, name, _ring_write_cols(buf, cached_index(col), value)
        )

    def update(
        self: TWindowedBinaryAUROC,
        input,
        target,
        weight: Optional[jax.Array] = None,
    ) -> TWindowedBinaryAUROC:
        """Insert a batch of samples into the ring buffers."""
        input, target = self._input(input), self._input(target)
        if weight is None:
            weight = default_ones(input.shape)
        else:
            weight = self._input_float(weight)
        _binary_auroc_update_input_check(input, target, self.num_tasks, weight)
        if input.ndim == 1:
            input = input.reshape(1, -1)
            target = target.reshape(1, -1)
            weight = weight.reshape(1, -1)
        target = target.astype(jnp.float32)
        n = input.shape[1]
        if n >= self.max_num_samples:
            # oversized batch: keep only its last max_num_samples samples
            self._write("inputs", 0, input[:, -self.max_num_samples :])
            self._write("targets", 0, target[:, -self.max_num_samples :])
            self._write("weights", 0, weight[:, -self.max_num_samples :])
            self.next_inserted = 0
        else:
            rest = self.max_num_samples - self.next_inserted
            if n <= rest:
                self._write("inputs", self.next_inserted, input)
                self._write("targets", self.next_inserted, target)
                self._write("weights", self.next_inserted, weight)
                self.next_inserted += n
            else:
                # wrap: first part fills the tail, remainder goes to the front
                self._write("inputs", self.next_inserted, input[:, :rest])
                self._write("targets", self.next_inserted, target[:, :rest])
                self._write("weights", self.next_inserted, weight[:, :rest])
                remainder = n - rest
                self._write("inputs", 0, input[:, -remainder:])
                self._write("targets", 0, target[:, -remainder:])
                self._write("weights", 0, weight[:, -remainder:])
                self.next_inserted = remainder
        self.next_inserted %= self.max_num_samples
        self.total_samples += n
        return self

    def compute(self) -> jax.Array:
        """AUROC per task over the windowed samples; empty before updates."""
        if self.total_samples == 0:
            return jnp.zeros(0)
        # partial-window detection matches the reference's zero-suffix probe
        # (reference window/auroc.py:170): only valid when real inputs are
        # nonzero, a quirk kept for parity.
        if bool(jnp.all(self.inputs[:, self.next_inserted :] == 0)):
            inputs = self.inputs[:, : self.next_inserted]
            targets = self.targets[:, : self.next_inserted]
            weights = self.weights[:, : self.next_inserted]
        else:
            inputs, targets, weights = self.inputs, self.targets, self.weights
        return _binary_auroc_compute(
            inputs.squeeze(), targets.squeeze(), weights.squeeze(), False
        )

    def merge_state(
        self: TWindowedBinaryAUROC, metrics: Iterable[TWindowedBinaryAUROC]
    ) -> TWindowedBinaryAUROC:
        """Pack all replicas' valid samples into enlarged buffers
        (reference window/auroc.py:181-238)."""
        metrics = list(metrics)
        merged_cols = self.max_num_samples + sum(m.max_num_samples for m in metrics)
        cur_size = min(self.total_samples, self.max_num_samples)
        new_bufs = {}
        for name in ("inputs", "targets", "weights"):
            buf = jnp.zeros((self.num_tasks, merged_cols))
            new_bufs[name] = buf.at[:, :cur_size].set(
                getattr(self, name)[:, :cur_size]
            )
        idx = cur_size
        for m in metrics:
            size = min(m.total_samples, m.max_num_samples)
            for name in ("inputs", "targets", "weights"):
                theirs = jax.device_put(
                    getattr(m, name)[:, :size], self._device
                )
                new_bufs[name] = new_bufs[name].at[:, idx : idx + size].set(theirs)
            idx += size
            self.total_samples += m.total_samples
        for name in ("inputs", "targets", "weights"):
            setattr(self, name, new_bufs[name])
        self.next_inserted = idx % self.max_num_samples
        return self
