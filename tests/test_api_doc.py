"""docs/api.md drift guard: every public export must be documented.

The reference generates its API docs from the package via Sphinx autodoc
(`/root/reference/docs/source/torcheval.metrics.rst` etc.), so its docs
cannot drift from the code. Ours are a hand-maintained markdown table;
this test restores the can't-drift property: adding a public symbol
without documenting it (or documenting a symbol that no longer exists)
fails here.
"""

import re
from pathlib import Path

import pytest

API_MD = (Path(__file__).parent.parent / "docs" / "api.md").read_text()

# `prefix.Symbol` occurrences inside backticks in the tables
DOCUMENTED = set(re.findall(r"`([\w.]+\.[\w]+)`", API_MD))

MODULES = [
    ("torcheval_tpu.metrics", "metrics"),
    ("torcheval_tpu.metrics.functional", "functional"),
    ("torcheval_tpu.metrics.toolkit", "toolkit"),
    ("torcheval_tpu.metrics.synclib", "synclib"),
    ("torcheval_tpu.metrics.sharded", "sharded"),
    ("torcheval_tpu.table", "table"),
    ("torcheval_tpu.distributed", "distributed"),
    ("torcheval_tpu.resilience", "resilience"),
    ("torcheval_tpu.elastic", "elastic"),
    ("torcheval_tpu.federation", "federation"),
    ("torcheval_tpu.obs", "obs"),
    ("torcheval_tpu.analysis", "analysis"),
    ("torcheval_tpu.tools", "tools"),
    ("torcheval_tpu.utils", "utils"),
    ("torcheval_tpu.utils.test_utils", "test_utils"),
    ("torcheval_tpu.parallel", "parallel"),
    ("torcheval_tpu.models", "models"),
    ("torcheval_tpu.ops.fused_auc", "ops.fused_auc"),
    ("torcheval_tpu.ops.segment", "ops.segment"),
    ("torcheval_tpu.ops.histogram", "ops.histogram"),
    ("torcheval_tpu.ops.topk", "ops.topk"),
]


def _public_exports(modname):
    import importlib
    import types
    import typing

    def _is_api(obj):
        # submodules and TypeVars are not documented API surface
        return not isinstance(obj, (types.ModuleType, typing.TypeVar))

    mod = importlib.import_module(modname)
    if hasattr(mod, "__all__"):
        return {n for n in mod.__all__ if _is_api(getattr(mod, n, None))}
    # no __all__: only names DEFINED here count as this module's exports
    # (imported helpers like toolkit's `Metric` are not its API surface)
    return {
        n
        for n in dir(mod)
        if not n.startswith("_")
        and _is_api(getattr(mod, n))
        and getattr(getattr(mod, n), "__module__", None) == modname
    }


@pytest.mark.parametrize("modname,prefix", MODULES)
def test_every_public_export_documented(modname, prefix):
    missing = {
        f"{prefix}.{name}"
        for name in _public_exports(modname)
        if f"{prefix}.{name}" not in DOCUMENTED
    }
    assert not missing, (
        f"public exports of {modname} missing from docs/api.md: "
        f"{sorted(missing)}"
    )


@pytest.mark.parametrize("modname,prefix", MODULES)
def test_no_stale_documented_symbols(modname, prefix):
    exports = _public_exports(modname)
    stale = {
        doc
        for doc in DOCUMENTED
        if doc.startswith(prefix + ".")
        # nested prefixes (e.g. "functional.x" vs "metrics.functional.x")
        and doc.count(".") == prefix.count(".") + 1
        and doc.rsplit(".", 1)[1] not in exports
    }
    assert not stale, (
        f"docs/api.md documents symbols {sorted(stale)} that "
        f"{modname} no longer exports"
    )
