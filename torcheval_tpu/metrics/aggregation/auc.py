"""AUC class metric.

Parity: reference torcheval/metrics/aggregation/auc.py:23-155 (list-buffered
x/y states, `_prepare_for_merge_state` concatenation).
"""

from __future__ import annotations

from typing import Iterable, Optional, TypeVar

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.functional.aggregation.auc import (
    _auc_compute,
    _auc_update_input_check,
)
from torcheval_tpu.metrics.metric import MergeKind, Metric

TAUC = TypeVar("TAUC", bound="AUC")


class AUC(Metric[jax.Array]):
    """Trapezoidal AUC of arbitrary (x, y) curves, buffered across updates.

    Args:
        reorder: stably sort buffered x before integrating (default True,
            matching the reference class default).
        n_tasks: number of independent curves per update.

    Examples::

        >>> from torcheval_tpu.metrics import AUC
        >>> metric = AUC()
        >>> metric.update(jnp.array([0., .5, 1.]), jnp.array([1., .5, 0.]))
        >>> metric.compute()
        Array([0.5], dtype=float32)
    """

    def __init__(
        self,
        *,
        reorder: bool = True,
        n_tasks: int = 1,
        device=None,
    ) -> None:
        super().__init__(device=device)
        self.reorder = reorder
        self.n_tasks = n_tasks
        self._add_state("x", [], merge=MergeKind.EXTEND)
        self._add_state("y", [], merge=MergeKind.EXTEND)

    def update(self: TAUC, x, y) -> TAUC:
        x, y = self._input(x), self._input(y)
        _auc_update_input_check(x, y, self.n_tasks)
        self.x.append(jnp.atleast_2d(x))
        self.y.append(jnp.atleast_2d(y))
        return self

    def compute(self) -> jax.Array:
        if not self.x:
            return jnp.zeros((0,))
        return _auc_compute(
            jnp.concatenate(self.x, axis=1),
            jnp.concatenate(self.y, axis=1),
            self.reorder,
        )

    def _prepare_for_merge_state(self) -> None:
        if self.x:
            self.x = [jnp.concatenate(self.x, axis=1)]
            self.y = [jnp.concatenate(self.y, axis=1)]
