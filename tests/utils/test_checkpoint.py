"""Checkpoint/resume round-trips through the Orbax-backed helpers, across
every TState kind (tensor counters, list buffers, dict states, int/float,
windowed ring buffers)."""

import os

import numpy as np
import pytest

import jax.numpy as jnp

from torcheval_tpu.metrics import (
    BinaryAUROC,
    MulticlassAccuracy,
    Throughput,
    WindowedBinaryNormalizedEntropy,
    WordErrorRate,
)
from torcheval_tpu.utils import load_metric_state, save_metric_state
from torcheval_tpu.utils.test_utils.dummy_metric import DummySumDictStateMetric
from torcheval_tpu.utils.test_utils.metric_class_tester import (
    assert_result_close,
)

RNG = np.random.default_rng(3)


def _roundtrip(tmp_path, metric, fresh):
    save_metric_state(metric, str(tmp_path / "ck"))
    load_metric_state(fresh, str(tmp_path / "ck"))
    return fresh


def test_counter_state_roundtrip(tmp_path):
    m = MulticlassAccuracy()
    m.update(jnp.asarray(RNG.random((16, 4)), jnp.float32), jnp.asarray(RNG.integers(0, 4, 16)))
    restored = _roundtrip(tmp_path, m, MulticlassAccuracy())
    assert_result_close(restored.compute(), m.compute())
    # resumable: updates continue after restore
    restored.update(jnp.zeros((4, 4)), jnp.zeros(4, dtype=jnp.int32))


def test_list_buffer_state_roundtrip(tmp_path):
    m = BinaryAUROC()
    for _ in range(3):
        x = RNG.random(20).astype(np.float32)
        m.update(x, (RNG.random(20) < x).astype(np.float32))
    restored = _roundtrip(tmp_path, m, BinaryAUROC())
    assert_result_close(restored.compute(), m.compute())


def test_empty_buffer_state_roundtrip(tmp_path):
    m = BinaryAUROC()  # no updates: empty buffers
    restored = _roundtrip(tmp_path, m, BinaryAUROC())
    assert restored.num_samples == 0


def test_float_state_roundtrip(tmp_path):
    m = Throughput()
    m.update(100, 2.5)
    restored = _roundtrip(tmp_path, m, Throughput())
    assert_result_close(restored.compute(), m.compute())


def test_host_float_text_state_roundtrip(tmp_path):
    m = WordErrorRate()
    m.update(["a b c"], ["a b d"])
    restored = _roundtrip(tmp_path, m, WordErrorRate())
    assert_result_close(restored.compute(), m.compute())


def test_dict_state_roundtrip(tmp_path):
    m = DummySumDictStateMetric()
    m.update("a", jnp.asarray(2.0))
    m.update("b", jnp.asarray(3.0))
    restored = _roundtrip(tmp_path, m, DummySumDictStateMetric())
    assert_result_close(restored.compute(), m.compute())
    # restored dict keeps auto-zero semantics for unseen keys
    restored.update("c", jnp.asarray(1.0))


def test_window_ring_buffer_roundtrip(tmp_path):
    m = WindowedBinaryNormalizedEntropy(max_num_updates=4)
    for _ in range(6):
        x = np.clip(RNG.random(10), 0.01, 0.99).astype(np.float64)
        m.update(x, (RNG.random(10) < 0.5).astype(np.float64))
    restored = _roundtrip(
        tmp_path, m, WindowedBinaryNormalizedEntropy(max_num_updates=4)
    )
    assert_result_close(restored.compute(), m.compute())


def test_collection_roundtrip(tmp_path):
    acc = MulticlassAccuracy()
    acc.update(jnp.asarray(RNG.random((8, 3)), jnp.float32), jnp.asarray(RNG.integers(0, 3, 8)))
    auroc = BinaryAUROC()
    x = RNG.random(16).astype(np.float32)
    auroc.update(x, (RNG.random(16) < x).astype(np.float32))
    save_metric_state({"acc": acc, "auroc": auroc}, str(tmp_path / "coll"))
    fresh = {"acc": MulticlassAccuracy(), "auroc": BinaryAUROC()}
    load_metric_state(fresh, str(tmp_path / "coll"))
    assert_result_close(fresh["acc"].compute(), acc.compute())
    assert_result_close(fresh["auroc"].compute(), auroc.compute())


def test_collection_strict_mismatch_both_directions(tmp_path):
    acc = MulticlassAccuracy()
    save_metric_state({"acc": acc}, str(tmp_path / "c2"))
    # collection requests a metric the checkpoint lacks
    with pytest.raises(RuntimeError, match="missing state for \\['other'\\]"):
        load_metric_state(
            {"acc": MulticlassAccuracy(), "other": BinaryAUROC()},
            str(tmp_path / "c2"),
        )
    # checkpoint holds state the collection doesn't claim
    save_metric_state(
        {"acc": acc, "extra": MulticlassAccuracy()}, str(tmp_path / "c3")
    )
    with pytest.raises(RuntimeError, match="unclaimed saved state"):
        load_metric_state({"acc": MulticlassAccuracy()}, str(tmp_path / "c3"))
    # non-strict: loads what exists
    load_metric_state(
        {"acc": MulticlassAccuracy(), "other": BinaryAUROC()},
        str(tmp_path / "c2"),
        strict=False,
    )


def test_single_vs_collection_kind_mismatch(tmp_path):
    acc = MulticlassAccuracy()
    save_metric_state({"acc": acc}, str(tmp_path / "coll"))
    with pytest.raises(RuntimeError, match="holds a metric collection"):
        load_metric_state(MulticlassAccuracy(), str(tmp_path / "coll"))
    save_metric_state(acc, str(tmp_path / "single"))
    with pytest.raises(RuntimeError, match="holds a single metric"):
        load_metric_state(
            {"acc": MulticlassAccuracy()}, str(tmp_path / "single")
        )


# ------------------------------------------- fault tolerance (ISSUE 2)


def _feed_acc(m):
    m.update(
        jnp.asarray(RNG.random((16, 4)), jnp.float32),
        jnp.asarray(RNG.integers(0, 4, 16)),
    )
    return m


def test_corrupt_checkpoint_rejected_with_clear_error(tmp_path):
    """Bit-flip a payload file: load must refuse with a digest error, not
    silently restore garbage into a resumed eval."""
    m = _feed_acc(MulticlassAccuracy())
    path = tmp_path / "ck"
    save_metric_state(m, str(path))
    # corrupt the largest data file under the checkpoint tree
    victim = max(
        (p for p in path.rglob("*") if p.is_file()),
        key=lambda p: p.stat().st_size,
    )
    blob = bytearray(victim.read_bytes())
    blob[len(blob) // 2] ^= 0xFF
    victim.write_bytes(bytes(blob))
    with pytest.raises(RuntimeError, match="corrupt"):
        load_metric_state(MulticlassAccuracy(), str(path))


def test_truncated_checkpoint_rejected(tmp_path):
    m = _feed_acc(MulticlassAccuracy())
    path = tmp_path / "ck"
    save_metric_state(m, str(path))
    victim = max(
        (p for p in path.rglob("*") if p.is_file()),
        key=lambda p: p.stat().st_size,
    )
    victim.write_bytes(victim.read_bytes()[: victim.stat().st_size // 2])
    with pytest.raises(RuntimeError, match="corrupt"):
        load_metric_state(MulticlassAccuracy(), str(path))


def test_missing_file_is_a_clear_error_not_garbage(tmp_path):
    m = _feed_acc(MulticlassAccuracy())
    path = tmp_path / "ck"
    save_metric_state(m, str(path))
    victim = max(
        (p for p in path.rglob("*") if p.is_file()),
        key=lambda p: p.stat().st_size,
    )
    victim.unlink()
    with pytest.raises(RuntimeError, match="corrupt or truncated|corrupt"):
        load_metric_state(MulticlassAccuracy(), str(path))


def test_save_is_atomic_under_mid_write_failure(tmp_path, monkeypatch):
    """A save that dies mid-write leaves the PREVIOUS checkpoint intact at
    the published path (write-temp-then-rename)."""
    import torcheval_tpu.utils.checkpoint as ckpt

    first = _feed_acc(MulticlassAccuracy())
    path = tmp_path / "ck"
    save_metric_state(first, str(path))

    class _ExplodingCheckpointer:
        def save(self, p, tree, force=False):
            # simulate dying AFTER partially writing the temp location
            os.makedirs(p, exist_ok=True)
            with open(os.path.join(p, "partial"), "w") as f:
                f.write("torn")
            raise RuntimeError("disk full")

    monkeypatch.setattr(ckpt, "_checkpointer", lambda: _ExplodingCheckpointer())
    second = _feed_acc(MulticlassAccuracy())
    with pytest.raises(RuntimeError, match="disk full"):
        save_metric_state(second, str(path))
    monkeypatch.undo()

    restored = load_metric_state(MulticlassAccuracy(), str(path))
    assert_result_close(restored.compute(), first.compute())


def test_overwrite_save_roundtrips(tmp_path):
    """Re-saving over an existing checkpoint path replaces it atomically."""
    path = tmp_path / "ck"
    save_metric_state(_feed_acc(MulticlassAccuracy()), str(path))
    newer = _feed_acc(MulticlassAccuracy())
    save_metric_state(newer, str(path))
    restored = load_metric_state(MulticlassAccuracy(), str(path))
    assert_result_close(restored.compute(), newer.compute())
    assert sorted(p.name for p in tmp_path.iterdir()) == ["ck"], (
        "temp/aside write locations must not leak"
    )


def test_legacy_checkpoint_without_digest_still_loads(tmp_path, monkeypatch):
    """Checkpoints written before the digest existed (or by older code)
    restore without an integrity check rather than erroring."""
    import torcheval_tpu.utils.checkpoint as ckpt

    m = _feed_acc(MulticlassAccuracy())
    path = tmp_path / "ck"
    monkeypatch.setattr(ckpt, "_digest", lambda tree: "00" * 32)
    save_metric_state(m, str(path))
    monkeypatch.undo()
    # strip the digest the way a legacy writer would never have added it
    tree = ckpt._checkpointer().restore(str(path))
    tree.pop("__digest__")
    ckpt._checkpointer().save(str(path), tree, force=True)
    restored = load_metric_state(MulticlassAccuracy(), str(path))
    assert_result_close(restored.compute(), m.compute())


def test_missing_checkpoint_is_file_not_found(tmp_path):
    """A checkpoint that was never written is FileNotFoundError — resume
    harnesses branch on missing (start fresh) vs corrupt (alert)."""
    with pytest.raises(FileNotFoundError, match="no metric checkpoint"):
        load_metric_state(MulticlassAccuracy(), str(tmp_path / "never"))


def test_overwrite_failure_rolls_previous_checkpoint_back(
    tmp_path, monkeypatch
):
    """If the final swap fails, the previous checkpoint is restored at the
    published path (it is renamed aside, never deleted first)."""
    import torcheval_tpu.utils.checkpoint as ckpt

    first = _feed_acc(MulticlassAccuracy())
    path = tmp_path / "ck"
    save_metric_state(first, str(path))

    real_rename = os.rename

    def failing_rename(src, dst):
        if src.endswith(".tmp"):
            raise OSError("simulated rename failure")
        return real_rename(src, dst)

    monkeypatch.setattr(ckpt.os, "rename", failing_rename)
    with pytest.raises(OSError, match="simulated"):
        save_metric_state(_feed_acc(MulticlassAccuracy()), str(path))
    monkeypatch.undo()

    restored = load_metric_state(MulticlassAccuracy(), str(path))
    assert_result_close(restored.compute(), first.compute())


def test_save_after_interrupted_save_preserves_aside_snapshot(
    tmp_path, monkeypatch
):
    """After a crash left the last good snapshot only at '<path>.old', a
    NEW save that itself fails must not destroy it: the aside copy is
    recovered to the published name before anything clobbers it."""
    import torcheval_tpu.utils.checkpoint as ckpt

    m = _feed_acc(MulticlassAccuracy())
    path = tmp_path / "ck"
    save_metric_state(m, str(path))
    os.rename(str(path), str(path) + ".old")  # crashed-swap disk state

    real_rename = os.rename

    def failing_rename(src, dst):
        if src.endswith(".tmp"):
            raise OSError("simulated rename failure")
        return real_rename(src, dst)

    monkeypatch.setattr(ckpt.os, "rename", failing_rename)
    with pytest.raises(OSError, match="simulated"):
        save_metric_state(_feed_acc(MulticlassAccuracy()), str(path))
    monkeypatch.undo()

    restored = load_metric_state(MulticlassAccuracy(), str(path))
    assert_result_close(restored.compute(), m.compute())


def test_crash_between_swap_renames_recovers_from_aside(tmp_path):
    """A crash AFTER the old checkpoint was renamed aside but BEFORE the
    new one landed leaves only '<path>.old'; load recovers it instead of
    reporting 'no checkpoint' (which would silently discard eval state)."""
    m = _feed_acc(MulticlassAccuracy())
    path = tmp_path / "ck"
    save_metric_state(m, str(path))
    # simulate the crash window: published path gone, aside copy present
    os.rename(str(path), str(path) + ".old")
    restored = load_metric_state(MulticlassAccuracy(), str(path))
    assert_result_close(restored.compute(), m.compute())
    assert os.path.exists(str(path))  # recovered back to the published name


def test_empty_buffer_digest_roundtrip(tmp_path):
    """The empty-array encoding (Orbax refuses zero-size arrays) must
    digest identically on save and load."""
    m = BinaryAUROC()  # fresh: empty (0,)-shaped lazy buffers
    path = tmp_path / "ck"
    save_metric_state(m, str(path))
    restored = load_metric_state(BinaryAUROC(), str(path))
    assert restored.num_samples == 0


def test_window_cursor_survives_resume(tmp_path):
    """Regression: a restored windowed metric must keep overwriting the
    OLDEST ring column; a parallel uninterrupted metric is the oracle."""
    rng = np.random.default_rng(8)
    batches = [
        (
            np.clip(rng.random(10), 0.01, 0.99).astype(np.float64),
            (rng.random(10) < 0.5).astype(np.float64),
        )
        for _ in range(10)
    ]
    uninterrupted = WindowedBinaryNormalizedEntropy(max_num_updates=4)
    first = WindowedBinaryNormalizedEntropy(max_num_updates=4)
    for x, t in batches[:6]:
        uninterrupted.update(x, t)
        first.update(x, t)
    save_metric_state(first, str(tmp_path / "cursor"))
    resumed = load_metric_state(
        WindowedBinaryNormalizedEntropy(max_num_updates=4),
        str(tmp_path / "cursor"),
    )
    assert resumed.next_inserted == first.next_inserted == 2
    for x, t in batches[6:]:
        uninterrupted.update(x, t)
        resumed.update(x, t)
    assert_result_close(resumed.compute(), uninterrupted.compute())


# ------------------------------------- restored-state validation (ISSUE 4)


def test_mismatched_shape_fails_naming_the_leaf(tmp_path):
    """A checkpoint from a differently-configured metric (another
    num_classes) must fail with an error naming the offending leaf path,
    not a cryptic downstream jax broadcast error."""
    from torcheval_tpu.metrics import MulticlassConfusionMatrix

    m = MulticlassConfusionMatrix(4)
    m.update(
        jnp.asarray(RNG.random((8, 4)), jnp.float32),
        jnp.asarray(RNG.integers(0, 4, 8)),
    )
    save_metric_state(m, str(tmp_path / "cm"))
    with pytest.raises(
        RuntimeError,
        match=r"state 'confusion_matrix' holds int32\[4, 4\] but "
        r"MulticlassConfusionMatrix registered int32\[8, 8\]",
    ):
        load_metric_state(MulticlassConfusionMatrix(8), str(tmp_path / "cm"))


def test_mismatched_collection_leaf_names_metric_prefix(tmp_path):
    from torcheval_tpu.metrics import MulticlassConfusionMatrix

    save_metric_state(
        {"cm": MulticlassConfusionMatrix(4)}, str(tmp_path / "coll")
    )
    with pytest.raises(RuntimeError, match="state 'cm.confusion_matrix'"):
        load_metric_state(
            {"cm": MulticlassConfusionMatrix(3)}, str(tmp_path / "coll")
        )


def test_kind_mismatch_fails_clearly(tmp_path, monkeypatch):
    """An array leaf where the metric registered a scalar state (a
    hand-edited or cross-version checkpoint) is caught by kind."""
    import torcheval_tpu.utils.checkpoint as ckpt

    m = Throughput()
    m.update(100, 2.5)
    save_metric_state(m, str(tmp_path / "tp"))
    tree = ckpt._checkpointer().restore(str(tmp_path / "tp"))
    tree["__single__"]["num_total"] = np.zeros(3, np.float32)
    monkeypatch.setattr(ckpt, "_digest", lambda t: "00" * 32)
    tree.pop("__digest__")
    ckpt._checkpointer().save(str(tmp_path / "tp"), tree, force=True)
    monkeypatch.undo()
    with pytest.raises(
        RuntimeError, match="'num_total' holds 'ndarray' but Throughput"
    ):
        load_metric_state(Throughput(), str(tmp_path / "tp"))


def test_growable_buffer_shapes_still_load(tmp_path):
    """Buffered metrics register a lazy 0-size sentinel; their restored
    buffers legitimately differ in shape/dtype and must keep loading."""
    m = BinaryAUROC()
    x = RNG.random(100).astype(np.float32)
    m.update(x, (RNG.random(100) < x).astype(np.float32))
    restored = _roundtrip(tmp_path, m, BinaryAUROC())
    assert_result_close(restored.compute(), m.compute())


# ---------------------------------- concurrent-writer detection (ISSUE 4)


def test_concurrent_writer_to_same_path_fails_loudly(tmp_path):
    """The fixed (pid-less) tmp/old sibling protocol is single-writer by
    design: a second live writer to the SAME path must fail loudly, not
    silently interleave renames."""
    import torcheval_tpu.utils.checkpoint as ckpt

    m = _feed_acc(MulticlassAccuracy())
    path = tmp_path / "ck"
    # a live writer's lock (fresh mtime)
    with open(str(path) + ".lock", "w") as f:
        f.write("pid=other t=now\n")
    with pytest.raises(RuntimeError, match="another save_metric_state writer"):
        save_metric_state(m, str(path))
    assert not path.exists()  # the contender wrote nothing
    # distinct paths never contend
    save_metric_state(m, str(tmp_path / "other"))


def test_stale_lock_from_crashed_writer_is_broken(tmp_path):
    """A lock left by a crashed writer (older than _LOCK_STALE_SECONDS)
    is broken with a warning instead of wedging every future save."""
    import torcheval_tpu.utils.checkpoint as ckpt

    m = _feed_acc(MulticlassAccuracy())
    path = tmp_path / "ck"
    lock = str(path) + ".lock"
    with open(lock, "w") as f:
        f.write("pid=dead\n")
    old = os.path.getmtime(lock) - ckpt._LOCK_STALE_SECONDS - 10
    os.utime(lock, (old, old))
    with pytest.warns(RuntimeWarning, match="breaking stale checkpoint lock"):
        save_metric_state(m, str(path))
    restored = load_metric_state(MulticlassAccuracy(), str(path))
    assert_result_close(restored.compute(), m.compute())
    assert not os.path.exists(lock)


def test_lock_released_after_failed_save(tmp_path, monkeypatch):
    """A save that raises must not leave its lock behind (the next save
    would misdiagnose a concurrent writer)."""
    import torcheval_tpu.utils.checkpoint as ckpt

    class _Exploding:
        def save(self, p, tree, force=False):
            raise RuntimeError("disk full")

    monkeypatch.setattr(ckpt, "_checkpointer", lambda: _Exploding())
    m = _feed_acc(MulticlassAccuracy())
    with pytest.raises(RuntimeError, match="disk full"):
        save_metric_state(m, str(tmp_path / "ck"))
    monkeypatch.undo()
    assert not os.path.exists(str(tmp_path / "ck") + ".lock")
    save_metric_state(m, str(tmp_path / "ck"))  # lock did not wedge
