// Fused AUC histogram — C++ XLA custom-call (CPU host kernel).
//
// The native component of the fused approximate-AUC op: the TPU path is the
// Pallas kernel in torcheval_tpu/ops/fused_auc.py; this is the host-side
// equivalent, registered with XLA through the FFI API so it participates in
// jit programs on the CPU backend. Parity target: the role of fbgemm_gpu's
// fused CUDA AUC kernel in the reference
// (torcheval/metrics/functional/classification/auroc.py:161-173).
//
// The WHOLE fused-AUC prep is inside the call — per-task min/max score
// normalization (use_bounds=0) or fixed-range scaling (use_bounds=1), and
// implicit unit weights (has_weight=0) — so the XLA side feeds raw scores
// and never materializes a normalized copy or a ones-weights array (those
// two prep passes cost more than the binning loop itself at 1M samples).
//
// Inputs:  scores (T, N) f32 (any range), labels (T, N) f32 {0, 1},
//          weights (T, N) f32 — or (T, 1) dummy when has_weight=0.
// Attrs:   has_weight, use_bounds (int64), lo, hi (double).
// Outputs: hist (T, 2, B) f32 — per task, row 0 = positive-weight histogram,
//          row 1 = negative-weight histogram over B equal score bins.
//
// NaN handling matches the XLA twin: with bounds=None a NaN poisons the
// whole task (every score maps to the 0.5 bin, as jnp.min/max propagate
// NaN through the normalize); with fixed bounds a NaN score lands in
// bin 0, sanitized BEFORE the float->int cast (converting NaN to int64
// is undefined behavior).
//
// Build: g++ -O3 -march=native -shared -fPIC (see native/__init__.py).

#include <algorithm>
#include <cstdint>

#include "xla/ffi/api/ffi.h"

namespace ffi = xla::ffi;

static ffi::Error FusedAucHistogramImpl(ffi::Buffer<ffi::F32> scores,
                                        ffi::Buffer<ffi::F32> labels,
                                        ffi::Buffer<ffi::F32> weights,
                                        ffi::ResultBuffer<ffi::F32> hist,
                                        int64_t has_weight, int64_t use_bounds,
                                        double lo_attr, double hi_attr) {
  const auto dims = scores.dimensions();
  if (dims.size() != 2) {
    return ffi::Error::InvalidArgument("scores must be rank 2 (tasks, n)");
  }
  const int64_t num_tasks = dims[0];
  const int64_t n = dims[1];
  const auto ldims = labels.dimensions();
  const auto wdims = weights.dimensions();
  if (ldims.size() != 2 || ldims[0] != num_tasks || ldims[1] != n) {
    return ffi::Error::InvalidArgument(
        "labels must match scores shape (tasks, n)");
  }
  if (wdims.size() != 2 || wdims[0] != num_tasks ||
      (has_weight && wdims[1] != n)) {
    return ffi::Error::InvalidArgument(
        "weights must be (tasks, n), or a (tasks, 1) dummy when "
        "has_weight=0");
  }
  const auto hist_dims = hist->dimensions();
  if (hist_dims.size() != 3 || hist_dims[0] != num_tasks ||
      hist_dims[1] != 2) {
    return ffi::Error::InvalidArgument("hist must be (tasks, 2, bins)");
  }
  const int64_t bins = hist_dims[2];

  const float* s = scores.typed_data();
  const float* l = labels.typed_data();
  const float* w = weights.typed_data();
  float* h = hist->typed_data();
  std::fill(h, h + num_tasks * 2 * bins, 0.0f);

  if (n == 0) {
    return ffi::Error::Success();  // zero histograms; no score to read
  }
  const float fbins = static_cast<float>(bins);
  for (int64_t t = 0; t < num_tasks; ++t) {
    float* pos = h + t * 2 * bins;
    float* neg = pos + bins;
    const int64_t base = t * n;

    float lo, span;
    if (use_bounds) {
      lo = static_cast<float>(lo_attr);
      // Subtract in double BEFORE narrowing: the XLA path bakes in
      // f32(hi - lo) at trace time, and f32(hi) - f32(lo) can differ
      // from it by 1 ULP (e.g. bounds (0.1, 0.3)), shifting edge
      // scores into a neighbouring bin and breaking backend parity.
      span = static_cast<float>(hi_attr - lo_attr);
    } else {
      // per-task min/max rescale: AUC is rank-invariant, so this makes
      // the binning correct for arbitrary score ranges (logits included).
      // Any NaN poisons the whole task exactly like jnp.min/max propagate
      // NaN in the XLA normalize (span NaN -> every score maps to 0.5);
      // a position-dependent skip here would make backends disagree.
      float smin = s[base], smax = s[base];
      bool has_nan = false;
      for (int64_t i = 0; i < n; ++i) {
        const float sc = s[base + i];
        has_nan |= sc != sc;
        smin = sc < smin ? sc : smin;
        smax = sc > smax ? sc : smax;
      }
      lo = smin;
      span = has_nan ? -1.0f : smax - smin;
    }
    // DIVISION, not multiply-by-reciprocal: the XLA paths normalize with
    // (s - lo) / span, and the backends-agree-exactly contract needs
    // bit-identical bin edges. Degenerate span maps every score to 0.5,
    // matching the XLA normalize; NaN scores fall through the clamps
    // into bin 0.
    for (int64_t i = 0; i < n; ++i) {
      float x = span > 0.0f ? (s[base + i] - lo) / span : 0.5f;
      x = x < 0.0f ? 0.0f : (x > 1.0f ? 1.0f : x);
      x = x == x ? x : 0.0f;  // NaN -> bin 0 BEFORE the cast (fp->int
                              // conversion of NaN is UB, not just junk)
      int64_t b = static_cast<int64_t>(x * fbins);
      b = b >= bins ? bins - 1 : b;
      const float wi = has_weight ? w[base + i] : 1.0f;
      const float li = l[base + i];
      pos[b] += wi * li;
      neg[b] += wi * (1.0f - li);
    }
  }
  return ffi::Error::Success();
}

XLA_FFI_DEFINE_HANDLER_SYMBOL(FusedAucHistogram, FusedAucHistogramImpl,
                              ffi::Ffi::Bind()
                                  .Arg<ffi::Buffer<ffi::F32>>()
                                  .Arg<ffi::Buffer<ffi::F32>>()
                                  .Arg<ffi::Buffer<ffi::F32>>()
                                  .Ret<ffi::Buffer<ffi::F32>>()
                                  .Attr<int64_t>("has_weight")
                                  .Attr<int64_t>("use_bounds")
                                  .Attr<double>("lo")
                                  .Attr<double>("hi"));
