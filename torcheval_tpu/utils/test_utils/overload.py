"""Deterministic overload generator: seeded spike schedules for tests
and benchmarks of the table admission layer.

An :class:`OverloadSchedule` is a scripted sequence of load phases —
``ramp`` / ``burst`` / ``sustained`` — each scaling the baseline batch
size (QPS) and key cardinality by a multiplier. Like
:class:`~torcheval_tpu.utils.test_utils.fault_injection.FaultInjectionGroup`,
nothing about the generated traffic depends on wall-clock or iteration
order: every batch is a pure function of ``(seed, step)`` (a fresh
``numpy`` generator per step), so a failing overload scenario replays
bit-identically from its seed alone, any single step can be regenerated
in isolation, and N thread-world ranks calling :meth:`batch` for the
same step synthesize the SAME traffic — which is what lets the
bit-identical-shed-decision tests compare admission across world sizes
without shipping arrays around.

The per-step key draw is uniform over a step-scaled key space: a
``cardinality`` multiplier widens the space, modeling the long-tail
blowup (new tenants / exploration traffic) that actually exhausts a
keyed table, while the QPS multiplier widens the batch. Payload columns
are synthesized per family (``ctr`` / ``weighted_calibration`` /
``ne`` / ``windowed_ne`` / ``hit_rate``) so one schedule can drive a
single-family table or every member of a
:class:`~torcheval_tpu.table.TablePanel`.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, NamedTuple, Sequence, Tuple

import numpy as np

__all__ = ["OverloadBatch", "OverloadPhase", "OverloadSchedule"]

_KINDS = ("ramp", "burst", "sustained")


class OverloadPhase(NamedTuple):
    """One scripted load phase.

    Args:
        kind: ``"ramp"`` (multiplier climbs 1 -> ``peak`` across the
            phase), ``"burst"`` (alternates ``peak`` / baseline every
            ``period`` steps, starting hot), or ``"sustained"`` (holds
            ``peak`` for the whole phase).
        steps: number of ingest steps in the phase.
        peak: QPS multiplier at the top of the phase (>= 1.0 for
            overload; < 1.0 models a lull).
        cardinality: key-cardinality multiplier applied with the same
            shape as the QPS multiplier (1.0 = key space stays at
            baseline even under the spike).
        period: burst on/off half-period in steps (``burst`` only).
    """

    kind: str
    steps: int
    peak: float
    cardinality: float = 1.0
    period: int = 4


class OverloadBatch(NamedTuple):
    """One synthesized ingest batch: pass ``keys`` positionally and
    ``kwargs`` by keyword to ``MetricTable.ingest`` (or one member
    bundle of a panel ingest)."""

    step: int
    keys: np.ndarray
    kwargs: Dict[str, Any]
    qps_multiplier: float
    cardinality_multiplier: float


def _phase_multipliers(phase: OverloadPhase) -> Iterator[Tuple[float, float]]:
    if phase.steps < 1:
        raise ValueError(f"phase steps must be >= 1, got {phase.steps}")
    if phase.kind not in _KINDS:
        raise ValueError(
            f"unknown overload phase kind {phase.kind!r}; one of {_KINDS}"
        )
    for i in range(phase.steps):
        if phase.kind == "ramp":
            frac = i / max(1, phase.steps - 1)
        elif phase.kind == "burst":
            if phase.period < 1:
                raise ValueError(
                    f"burst period must be >= 1, got {phase.period}"
                )
            frac = 1.0 if (i // phase.period) % 2 == 0 else 0.0
        else:  # sustained
            frac = 1.0
        yield (
            1.0 + frac * (phase.peak - 1.0),
            1.0 + frac * (phase.cardinality - 1.0),
        )


class OverloadSchedule:
    """A scripted, seeded load schedule (module docstring).

    Args:
        phases: the scripted :class:`OverloadPhase` sequence.
        base_rows: baseline batch size at multiplier 1.0.
        base_keys: baseline key-space size at cardinality 1.0.
        seed: replay seed; every batch is a pure function of
            ``(seed, step)``.
        family: payload family synthesized by :meth:`batch` /
            :meth:`batches` (``ctr`` | ``weighted_calibration`` |
            ``ne`` | ``windowed_ne`` | ``hit_rate``).
    """

    def __init__(
        self,
        phases: Sequence[OverloadPhase],
        *,
        base_rows: int = 64,
        base_keys: int = 32,
        seed: int = 0,
        family: str = "ctr",
    ) -> None:
        phases = [
            p if isinstance(p, OverloadPhase) else OverloadPhase(*p)
            for p in phases
        ]
        if not phases:
            raise ValueError("an OverloadSchedule needs at least one phase")
        if base_rows < 1 or base_keys < 1:
            raise ValueError(
                f"base_rows/base_keys must be >= 1, got "
                f"{base_rows}/{base_keys}"
            )
        self.phases = tuple(phases)
        self.base_rows = int(base_rows)
        self.base_keys = int(base_keys)
        self.seed = int(seed)
        self.family = str(family)
        self._multipliers: Tuple[Tuple[float, float], ...] = tuple(
            m for p in self.phases for m in _phase_multipliers(p)
        )

    # ------------------------------------------------------------ shapes

    @classmethod
    def ramp(cls, steps: int, peak: float, **kwargs: Any) -> "OverloadSchedule":
        """Baseline -> ``peak`` climb over ``steps``."""
        card = float(kwargs.pop("cardinality", 1.0))
        return cls([OverloadPhase("ramp", steps, peak, card)], **kwargs)

    @classmethod
    def burst(
        cls, steps: int, peak: float, period: int = 4, **kwargs: Any
    ) -> "OverloadSchedule":
        """Alternating ``peak`` / baseline every ``period`` steps."""
        card = float(kwargs.pop("cardinality", 1.0))
        return cls(
            [OverloadPhase("burst", steps, peak, card, period)], **kwargs
        )

    @classmethod
    def sustained(
        cls, steps: int, peak: float, **kwargs: Any
    ) -> "OverloadSchedule":
        """``peak`` held for all ``steps``."""
        card = float(kwargs.pop("cardinality", 1.0))
        return cls([OverloadPhase("sustained", steps, peak, card)], **kwargs)

    # ------------------------------------------------------------- steps

    def __len__(self) -> int:
        return len(self._multipliers)

    def multiplier(self, step: int) -> Tuple[float, float]:
        """``(qps_multiplier, cardinality_multiplier)`` at ``step``."""
        return self._multipliers[step]

    def rows_at(self, step: int) -> int:
        return max(1, int(round(self.base_rows * self._multipliers[step][0])))

    def keyspace_at(self, step: int) -> int:
        return max(1, int(round(self.base_keys * self._multipliers[step][1])))

    def _rng(self, step: int) -> np.random.Generator:
        # (seed, step)-keyed generator: any step replays in isolation
        return np.random.default_rng((self.seed, step))

    def batch(self, step: int) -> OverloadBatch:
        """Synthesize the batch for ``step`` — pure in ``(seed, step)``."""
        qps, card = self._multipliers[step]
        n = self.rows_at(step)
        space = self.keyspace_at(step)
        rng = self._rng(step)
        keys = rng.integers(0, space, size=n).astype(np.int64)
        kwargs: Dict[str, Any]
        if self.family == "ctr":
            kwargs = {
                "clicks": rng.integers(0, 2, size=n).astype(np.float32),
                "weights": 1.0,
            }
        elif self.family == "weighted_calibration":
            kwargs = {
                "preds": rng.random(n).astype(np.float32),
                "targets": rng.integers(0, 2, size=n).astype(np.float32),
                "weights": 1.0,
            }
        elif self.family in ("ne", "windowed_ne"):
            kwargs = {
                "preds": np.clip(
                    rng.random(n).astype(np.float32), 0.01, 0.99
                ),
                "targets": rng.integers(0, 2, size=n).astype(np.float32),
                "weights": 1.0,
            }
        elif self.family == "hit_rate":
            kwargs = {
                "scores": rng.random((n, 8)).astype(np.float32),
                "targets": rng.integers(0, 8, size=n).astype(np.int64),
            }
        else:
            raise ValueError(
                f"no synthesized payload for table family {self.family!r}"
            )
        return OverloadBatch(step, keys, kwargs, qps, card)

    def batches(self) -> Iterator[OverloadBatch]:
        """All scripted batches in step order."""
        for step in range(len(self)):
            yield self.batch(step)

    def total_rows(self) -> int:
        return sum(self.rows_at(s) for s in range(len(self)))
