from torcheval_tpu.utils.test_utils.dummy_metric import (
    DummySumDictStateMetric,
    DummySumListStateMetric,
    DummySumMetric,
)

__all__ = [
    "DummySumMetric",
    "DummySumListStateMetric",
    "DummySumDictStateMetric",
]
