"""Metric-program verifier: static checks at the jaxpr/HLO layer.

Traces a function (or a metric's fused update plan) with abstract inputs —
``jax.make_jaxpr`` for the primitive-level view, ``jax.jit(...).lower()``
plus a fully-optimized compile for the XLA view — and checks the library's
core contracts WITHOUT executing a step:

- **no host escapes**: no ``pure_callback`` / ``io_callback`` /
  ``debug_callback`` primitives on the update path (the transfer-guard
  tests catch runtime transfers; this catches the callback class those
  guards cannot see until the callback actually fires);
- **collective census**: the count AND ordered opcode sequence of
  collectives, checked against declared expectations — the
  zero-added-collectives north star becomes a one-line assertion
  (``expect_collectives=0`` for local update programs,
  :func:`compare_collective_sequences` for full synced steps);
- **donation soundness**: every donated invar must appear in the
  compiled module's ``input_output_alias`` (jax only warns), and — at
  the call layer — no donated buffer may be passed twice or also appear
  in a non-donated position (the read-after-consume bug class PR 6's
  reviews caught by hand, now checked by
  :func:`check_donation_aliasing`);
- **dtype safety**: 64-bit avals (accidental f64/i64 promotion that
  changes numerics between x64-enabled and -disabled runs) and silent
  64→32-bit narrowing casts (the int64 wire downcast class fixed in
  PR 2). The int32 id-arithmetic wrap funnel is handled constructively
  by ``ops.segment.safe_ids``; this rule guards the promotion/narrowing
  class around it.

The runtime pins that predate this module (transfer-guard no-host-sync,
donation pointer stability) are kept available here as
``assert_update_transfer_free`` / ``assert_donated_update_in_place`` so
the legacy tier-1 tests are thin wrappers over one API.
"""

from __future__ import annotations

import copy
import re
from dataclasses import dataclass, field
from typing import (
    Any,
    Dict,
    Iterable,
    List,
    Optional,
    Sequence,
    Tuple,
    Union,
)

import jax
import jax.numpy as jnp

from torcheval_tpu.analysis.report import Finding, Report, set_last_report
from torcheval_tpu.utils import hlo as hlo_utils

__all__ = [
    "ProgramReport",
    "assert_donated_update_in_place",
    "assert_update_transfer_free",
    "check_donation_aliasing",
    "compare_collective_sequences",
    "verify_metric_compute",
    "verify_metric_merge",
    "verify_metric_update",
    "verify_program",
]

# jaxpr-level cross-replica collective primitives (the lax.p* family and
# the gather/scatter forms sync_states_in_jit can emit). ``psum2`` is the
# spelling shard_map's replication-rewrite emits on jax 0.4.37+.
# Deliberately NOT listed: ``pbroadcast`` — the rewrite inserts it as a
# device-local replication cast that lowers to no communication, so
# counting it would fake collective divergence between programs that
# differ only in replication bookkeeping.
COLLECTIVE_PRIMITIVES = frozenset(
    {
        "psum",
        "psum2",
        "pmax",
        "pmin",
        "ppermute",
        "all_gather",
        "all_gather_invariant",
        "all_to_all",
        "pgather",
        "psum_scatter",
        "reduce_scatter",
    }
)

# 64-bit-PRECISION dtypes — the ones whose numerics change between
# x64-enabled and -disabled runs. Matched by name, not itemsize: complex64
# is 8 bytes but 32-bit precision (no x64 hazard), while complex128 is the
# 16-byte one an itemsize==8 test would miss.
_64BIT_DTYPES = frozenset({"int64", "uint64", "float64", "complex128"})
_32BIT_DTYPES = frozenset({"int32", "uint32", "float32", "complex64"})

# Host-escape primitives: anything lowering to a host callback. Matched by
# exact name or the "callback" substring so new jax spellings fail closed.
_HOST_ESCAPE_EXACT = frozenset({"debug_print", "host_local_array_to_global"})


def _is_host_escape(prim_name: str) -> bool:
    return "callback" in prim_name or prim_name in _HOST_ESCAPE_EXACT


def _eqn_provenance(eqn) -> str:
    try:
        from jax._src import source_info_util

        return source_info_util.summarize(eqn.source_info)
    except Exception:  # pragma: no cover - jax-internal API drift
        return "<unknown>"


try:
    # The stable home since jax 0.4.35; the jax.core spellings were
    # removed from the public namespace in jax >= 0.6, which pyproject's
    # jax>=0.9 floor installs in CI.
    from jax.extend.core import ClosedJaxpr as _ClosedJaxpr
    from jax.extend.core import Jaxpr as _Jaxpr
except ImportError:  # pragma: no cover - pre-jax.extend.core releases
    from jax.core import ClosedJaxpr as _ClosedJaxpr
    from jax.core import Jaxpr as _Jaxpr


def _sub_jaxprs(params: Dict[str, Any]):
    """Every sub-jaxpr reachable from one eqn's params (cond branches,
    while cond/body, scan/jit bodies, custom_* calls)."""
    for value in params.values():
        if isinstance(value, _ClosedJaxpr):
            yield value.jaxpr
        elif isinstance(value, _Jaxpr):
            yield value
        elif isinstance(value, (tuple, list)):
            for item in value:
                if isinstance(item, _ClosedJaxpr):
                    yield item.jaxpr
                elif isinstance(item, _Jaxpr):
                    yield item


def iter_eqns(jaxpr):
    """Depth-first, program-order traversal of a jaxpr and every
    sub-jaxpr (shared by the verifier and the lockstep checker)."""
    for eqn in jaxpr.eqns:
        yield eqn
        for sub in _sub_jaxprs(eqn.params):
            yield from iter_eqns(sub)


def _abstractize(x: Any) -> Any:
    """Concrete array leaves -> ShapeDtypeStruct (verification must not
    depend on values, and must not upload anything)."""

    def leaf(v):
        if isinstance(v, (jax.Array,)) or type(v).__module__ == "numpy":
            arr = jnp.shape(v), jnp.result_type(v)
            return jax.ShapeDtypeStruct(arr[0], arr[1])
        return v

    return jax.tree_util.tree_map(leaf, x)


@dataclass
class ProgramReport(Report):
    """A :class:`Report` plus the traced program's census, for callers
    that assert on structure directly."""

    name: str = "<program>"
    collectives: Tuple[str, ...] = ()  # jaxpr primitive names, in order
    hlo_collectives: Tuple[str, ...] = ()  # optimized-HLO opcodes, in order
    host_escapes: Tuple[str, ...] = ()
    donated_params: Tuple[int, ...] = ()
    aliased_params: Tuple[int, ...] = ()
    jaxpr_text: str = ""

    def __post_init__(self):
        self.tool = "program"

    def as_dict(self) -> Dict[str, Any]:
        out = super().as_dict()
        out.update(
            name=self.name,
            collectives=list(self.collectives),
            hlo_collectives=list(self.hlo_collectives),
            host_escapes=list(self.host_escapes),
            donated_params=list(self.donated_params),
            aliased_params=list(self.aliased_params),
        )
        return out


def _finding(report: ProgramReport, rule: str, message: str, **kw) -> None:
    report.findings.append(
        Finding(
            tool="program", rule=rule, path=report.name, message=message, **kw
        )
    )


# One alias entry of the module header's input_output_alias table, e.g.
# `{0}: (0, {}, may-alias)` — param number captured. The table nests
# braces (`input_output_alias={ {0}: (0, {}, may-alias), ... }`), so the
# pairs are matched directly off the header line rather than trying to
# regex-delimit the block.
_ALIAS_PAIR = re.compile(
    r"\(\s*(\d+)\s*,\s*\{[^{}]*\}\s*,\s*(?:may|must)[-_]alias\s*\)"
)


def _aliased_param_numbers(hlo_text: str) -> Tuple[int, ...]:
    for line in hlo_text.splitlines():
        if "input_output_alias=" in line:
            seg = line.split("input_output_alias=", 1)[1]
            return tuple(
                sorted({int(p) for p in _ALIAS_PAIR.findall(seg)})
            )
    return ()


def _donated_flat_indices(
    args: Sequence[Any], donate_argnums: Sequence[int]
) -> Tuple[int, ...]:
    """Flat parameter indices (jit flattening order) of the donated
    top-level arguments."""
    donated: List[int] = []
    offset = 0
    for i, arg in enumerate(args):
        leaves = jax.tree_util.tree_leaves(arg)
        n = len(leaves)
        if i in donate_argnums:
            donated.extend(range(offset, offset + n))
        offset += n
    return tuple(donated)


def verify_program(
    fn,
    *args: Any,
    name: Optional[str] = None,
    donate_argnums: Sequence[int] = (),
    expect_collectives: Optional[Union[int, Sequence[str]]] = None,
    expect_hlo_collectives: Optional[Union[int, Sequence[str]]] = None,
    allow_host_escapes: bool = False,
    check_dtypes: bool = True,
    compile_hlo: bool = True,
) -> ProgramReport:
    """Statically verify one traceable program against the rule set.

    ``args`` may be concrete arrays or ``ShapeDtypeStruct``s — concrete
    leaves are abstracted before tracing, so nothing executes. With
    ``donate_argnums``, donation soundness is checked on the OPTIMIZED
    module's ``input_output_alias`` table. ``expect_collectives`` pins
    the jaxpr-level census (an int pins the count, a sequence pins the
    ordered primitive names); ``expect_hlo_collectives`` does the same
    for optimized-HLO opcodes (``utils.hlo.collective_sequence``).
    """
    label = name or getattr(fn, "__name__", None) or "<program>"
    report = ProgramReport(tool="program", name=label, checked=1)
    abstract_args = tuple(_abstractize(a) for a in args)

    closed = jax.make_jaxpr(fn)(*abstract_args)
    report.jaxpr_text = str(closed)

    collectives: List[str] = []
    escapes: List[str] = []
    for eqn in iter_eqns(closed.jaxpr):
        pname = eqn.primitive.name
        if pname in COLLECTIVE_PRIMITIVES:
            collectives.append(pname)
        if _is_host_escape(pname):
            escapes.append(pname)
            if not allow_host_escapes:
                _finding(
                    report,
                    "host-callback",
                    f"host escape `{pname}` in the traced program at "
                    f"{_eqn_provenance(eqn)} — callbacks force a host "
                    "round trip per step and break the async dispatch "
                    "contract",
                )
        if check_dtypes:
            for var in tuple(eqn.invars) + tuple(eqn.outvars):
                aval = getattr(var, "aval", None)
                dtype = getattr(aval, "dtype", None)
                if dtype is not None and jnp.dtype(dtype).name in _64BIT_DTYPES:
                    _finding(
                        report,
                        "dtype-64bit",
                        f"64-bit value ({jnp.dtype(dtype).name}) flows "
                        f"through `{pname}` at {_eqn_provenance(eqn)}: "
                        "numerics silently change between x64-enabled "
                        "and -disabled runs",
                    )
                    break  # one finding per eqn is enough
            if eqn.primitive.name == "convert_element_type":
                src = getattr(eqn.invars[0], "aval", None)
                dst = eqn.params.get("new_dtype")
                if (
                    src is not None
                    and dst is not None
                    and jnp.dtype(src.dtype).name in _64BIT_DTYPES
                    and jnp.dtype(dst).name in _32BIT_DTYPES
                ):
                    _finding(
                        report,
                        "dtype-narrowing",
                        f"silent 64->32-bit cast "
                        f"({jnp.dtype(src.dtype).name} -> "
                        f"{jnp.dtype(dst).name}) at "
                        f"{_eqn_provenance(eqn)}: the wire-downcast bug "
                        "class — make the narrowing explicit and "
                        "range-checked (see distributed.encode_length)",
                    )
    report.collectives = tuple(collectives)
    report.host_escapes = tuple(escapes)

    if expect_collectives is not None:
        _check_census(
            report, "collective-census", report.collectives, expect_collectives
        )

    if compile_hlo:
        jitted = jax.jit(fn, donate_argnums=tuple(donate_argnums))
        compiled = hlo_utils.compile_fully_optimized(
            jitted.lower(*abstract_args)
        )
        hlo_text = compiled.as_text()
        report.hlo_collectives = hlo_utils.collective_sequence(hlo_text)
        if expect_hlo_collectives is not None:
            _check_census(
                report,
                "collective-census",
                report.hlo_collectives,
                expect_hlo_collectives,
            )
        if donate_argnums:
            report.donated_params = _donated_flat_indices(
                abstract_args, tuple(donate_argnums)
            )
            report.aliased_params = _aliased_param_numbers(hlo_text)
            missing = sorted(
                set(report.donated_params) - set(report.aliased_params)
            )
            if missing:
                flat = [
                    leaf
                    for a in abstract_args
                    for leaf in jax.tree_util.tree_leaves(a)
                ]
                # the zero-realloc contract is about BUFFERS; a 0-d
                # scalar XLA chose not to alias (e.g. a derived state the
                # kernel recomputes instead of reads) costs nothing per
                # step — reported, but as an auditable warning
                buffers = [i for i in missing if getattr(flat[i], "shape", ())]
                scalars = [i for i in missing if i not in buffers]
                if buffers:
                    _finding(
                        report,
                        "donated-not-aliased",
                        f"donated parameter(s) {buffers} missing from the "
                        "compiled module's input_output_alias: XLA could "
                        "not reuse the donated buffer (jax only warns) — "
                        "the zero-realloc contract silently does not hold",
                    )
                if scalars:
                    _finding(
                        report,
                        "donated-not-aliased",
                        f"donated 0-d scalar parameter(s) {scalars} not "
                        "aliased in the optimized module (reallocating a "
                        "scalar is free; flagged for audit only)",
                        severity="warning",
                    )
    return set_last_report(report)


def _check_census(
    report: ProgramReport,
    rule: str,
    got: Tuple[str, ...],
    expect: Union[int, Sequence[str]],
) -> None:
    if isinstance(expect, int):
        if len(got) != expect:
            _finding(
                report,
                rule,
                f"expected {expect} collective(s), found {len(got)}: "
                f"{list(got)}",
            )
    elif tuple(got) != tuple(expect):
        _finding(
            report,
            rule,
            f"collective sequence {list(got)} != declared expectation "
            f"{list(expect)} (order matters: reordering breaks rank "
            "lockstep even at equal counts)",
        )


# -------------------------------------------------- donation (call layer)


def _buffer_key(leaf: Any):
    if isinstance(leaf, jax.Array):
        try:
            return ("ptr", leaf.unsafe_buffer_pointer())
        except Exception:  # sharded/committed arrays: fall back to identity
            return ("id", id(leaf))
    return None


def check_donation_aliasing(
    args: Sequence[Any],
    donate_argnums: Sequence[int],
    *,
    name: str = "<call>",
) -> Report:
    """Call-layer donation soundness for one concrete call: no donated
    buffer may appear twice among the donated leaves (XLA would write
    one output over another's input), and no donated buffer may ALSO be
    passed in a non-donated position (it would be read after the donated
    alias consumed it) — PR 6's hand-caught review bug class as a check.
    """
    report = Report(tool="program")
    report.checked = 1
    donate = set(donate_argnums)
    seen_donated: Dict[Any, str] = {}
    plain: Dict[Any, str] = {}
    for i, arg in enumerate(args):
        for j, leaf in enumerate(jax.tree_util.tree_leaves(arg)):
            key = _buffer_key(leaf)
            if key is None:
                continue
            where = f"arg {i} leaf {j}"
            if i in donate:
                if key in seen_donated:
                    report.findings.append(
                        Finding(
                            tool="program",
                            rule="donated-twice",
                            path=name,
                            message=(
                                f"the same buffer is donated at "
                                f"{seen_donated[key]} and {where}: XLA "
                                "aliases both outputs onto one buffer — "
                                "one result silently overwrites the other"
                            ),
                        )
                    )
                seen_donated[key] = where
            else:
                plain[key] = where
    for key, where in seen_donated.items():
        if key in plain:
            report.findings.append(
                Finding(
                    tool="program",
                    rule="donated-also-read",
                    path=name,
                    message=(
                        f"buffer donated at {where} is also passed "
                        f"un-donated at {plain[key]}: it will be read "
                        "after the donated alias consumed it"
                    ),
                )
            )
    return report


# ------------------------------------------------------- metric verifiers


def _normalized_plan(metric, *args, **kwargs):
    """(kernel, state_names, dynamic, config, transform, plan-or-None);
    the trailing entry is the raw :class:`UpdatePlan` when the metric
    declares one (so the caller can reach ``masked_kernel``). ``kwargs``
    forward to ``_update_plan`` (keyword-only update forms like
    WeightedCalibration's ``task_ids=``)."""
    from torcheval_tpu.metrics.metric import UpdatePlan

    plan = metric._update_plan(*args, **kwargs)
    if plan is None:
        return None
    if isinstance(plan, UpdatePlan):
        return (
            plan.kernel,
            plan.state_names,
            plan.dynamic,
            plan.config,
            plan.transform,
            plan,
        )
    kernel, state_names, dynamic, *rest = plan
    return kernel, state_names, dynamic, (rest[0] if rest else ()), False, None


def _abstract_bucketed_dynamic(plan) -> Tuple[Any, ...]:
    """The masked-kernel variant's abstract argument avals: every batch
    axis padded to its power-of-two bucket, plus the int32 valid-extent
    vector. Mirrors the SHAPE logic of ``_bucket.apply_bucketing`` (the
    dispatch that actually runs under ``config.shape_bucketing()``) at
    the aval level, so the verifier covers the bucketed program without
    touching the knob, the device, or concrete padding."""
    from torcheval_tpu.metrics import _bucket

    sizes: Dict[str, int] = {}
    order: List[str] = []
    for arg, labels in zip(plan.dynamic, plan.batch_axes):
        for axis, label in enumerate(labels or ()):
            n = int(jnp.shape(arg)[axis])
            if label not in sizes:
                sizes[label] = n
                order.append(label)
    buckets = {label: _bucket.bucket_length(n) for label, n in sizes.items()}
    padded = []
    for arg, labels in zip(plan.dynamic, plan.batch_axes):
        shape = list(jnp.shape(arg))
        for axis, label in enumerate(labels or ()):
            shape[axis] = buckets[label]
        padded.append(
            jax.ShapeDtypeStruct(tuple(shape), jnp.result_type(arg))
        )
    return tuple(padded) + (jax.ShapeDtypeStruct((len(order),), jnp.int32),)


def verify_metric_update(
    metric,
    *args: Any,
    donate: Optional[bool] = None,
    expect_collectives: Union[int, Sequence[str]] = 0,
    **update_kwargs: Any,
) -> Optional[ProgramReport]:
    """Statically verify a metric's fused update program: no host
    escapes, zero collectives (a LOCAL update must never sync), dtype
    safety, and — by default, regardless of the process donation knob —
    donation soundness of the donated program variant plus call-layer
    aliasing of the metric's live states. Returns ``None`` for metrics
    whose update has no fusable plan (host-side text metrics, buffered
    appends — their donated-append discipline is pinned by
    tests/metrics/test_buffers.py)."""
    from torcheval_tpu.metrics import _fuse

    normalized = _normalized_plan(metric, *args, **update_kwargs)
    if normalized is None:
        return None
    kernel, state_names, dynamic, config, transform, plan = normalized
    states = tuple(getattr(metric, n) for n in state_names)
    if donate is None:
        donate = metric._donated_update

    def _fused(use_kernel):
        if transform:

            def fused(states, *dyn):
                return _fuse._apply_transform(use_kernel, config, states, dyn)

        else:

            def fused(states, *dyn):
                return _fuse._apply_kernel(use_kernel, config, states, dyn)

        return fused

    report = verify_program(
        _fused(kernel),
        states,
        *dynamic,
        name=f"{type(metric).__name__}.update",
        donate_argnums=(0,) if donate else (),
        expect_collectives=expect_collectives,
    )
    if plan is not None and plan.masked_kernel is not None and plan.batch_axes:
        # under config.shape_bucketing() the metric dispatches the MASKED
        # kernel over padded buckets — verify that program too (same
        # contracts), regardless of the process knob: certifying only the
        # unbucketed twin would bless a program production never runs
        report.extend(
            verify_program(
                _fused(plan.masked_kernel),
                states,
                *_abstract_bucketed_dynamic(plan),
                name=f"{type(metric).__name__}.update[bucketed]",
                donate_argnums=(0,) if donate else (),
                expect_collectives=expect_collectives,
            )
        )
    if donate:
        call_report = check_donation_aliasing(
            (states,) + tuple(dynamic),
            (0,),
            name=report.name,
        )
        report.extend(call_report)
    return set_last_report(report)


def _abstract_states(metric) -> Dict[str, Any]:
    """Array-valued states as abstract leaves (int/float states stay
    concrete host scalars — they are not device state)."""
    out = {}
    for sname in metric._state_name_to_default:
        value = getattr(metric, sname)
        if isinstance(value, (jax.Array, list, dict)):
            out[sname] = _abstractize(
                list(value) if isinstance(value, list) else
                dict(value) if isinstance(value, dict) else value
            )
    return out


def verify_metric_compute(metric) -> ProgramReport:
    """Statically trace ``compute()`` over abstract states. A compute
    that CONCRETIZES device state (``float(arr)``, ``if arr:``) fails to
    trace — reported as a ``compute-host-sync`` warning (compute is
    host-side finalization, off the hot path, so this is informational
    by house rules — the hard no-host-sync contract binds ``update``)."""
    clone = copy.deepcopy(metric)
    names = sorted(_abstract_states(clone))

    def run(state_values):
        for sname, value in zip(names, state_values):
            setattr(clone, sname, value)
        return clone.compute()

    abstract = tuple(_abstract_states(clone)[n] for n in names)
    name = f"{type(metric).__name__}.compute"
    try:
        report = verify_program(
            run, abstract, name=name, expect_collectives=0, compile_hlo=False
        )
    except (
        jax.errors.ConcretizationTypeError,
        jax.errors.TracerArrayConversionError,
        jax.errors.TracerBoolConversionError,
    ) as exc:
        report = ProgramReport(tool="program", name=name, checked=1)
        first_line = str(exc).strip().splitlines()[0]
        _finding(
            report,
            "compute-host-sync",
            f"compute() reads device values on the host ({first_line})",
            severity="warning",
        )
        report = set_last_report(report)
    except RuntimeError as exc:
        # ONLY the buffered no-data precondition (_buffer.py: "has no
        # data: call update() before compute()") is a non-verdict —
        # callers wanting a real trace should update once first. Any
        # other RuntimeError is a genuine compute() defect and must not
        # be downgraded to a warning the CI gate would wave through.
        if "call update() before" not in str(exc):
            raise
        report = ProgramReport(tool="program", name=name, checked=1)
        _finding(
            report,
            "compute-untraceable",
            f"compute() not traceable on this instance ({exc}); update "
            "the metric once before verifying compute",
            severity="warning",
        )
        report = set_last_report(report)
    return report


def verify_metric_merge(metric) -> ProgramReport:
    """Statically trace the declarative ``merge_state`` program (two
    abstract replicas): no host escapes, no collectives (merge itself is
    local math — collectives belong to the sync transport), dtype-safe."""
    mine = copy.deepcopy(metric)
    theirs = copy.deepcopy(metric)
    names = sorted(_abstract_states(mine))

    def run(mine_states, theirs_states):
        for sname, value in zip(names, mine_states):
            setattr(mine, sname, value)
        for sname, value in zip(names, theirs_states):
            setattr(theirs, sname, value)
        mine.merge_state([theirs])
        return tuple(getattr(mine, sname) for sname in names)

    abstract = tuple(_abstract_states(mine)[n] for n in names)
    return verify_program(
        run,
        abstract,
        abstract,
        name=f"{type(metric).__name__}.merge_state",
        expect_collectives=0,
        compile_hlo=False,
    )


# --------------------------------------------- zero-added-collectives diff


def compare_collective_sequences(
    baseline_fn,
    baseline_args: Sequence[Any],
    synced_fn,
    synced_args: Sequence[Any],
    *,
    name: str = "<step>",
    allow_added: Union[int, Sequence[str]] = 0,
) -> ProgramReport:
    """Compile both step programs fully optimized and diff their ordered
    HLO collective sequences — the zero-added-collectives property as
    one call. ``allow_added`` relaxes the pin where an addition is the
    declared cost (e.g. one ``all-gather`` for an EXTEND state): an int
    bounds the number of added ops, a sequence pins exactly which
    opcodes may be added (as a multiset)."""
    base = hlo_utils.collective_sequence(
        hlo_utils.compile_fully_optimized(
            jax.jit(baseline_fn).lower(*map(_abstractize, baseline_args))
        )
    )
    synced = hlo_utils.collective_sequence(
        hlo_utils.compile_fully_optimized(
            jax.jit(synced_fn).lower(*map(_abstractize, synced_args))
        )
    )
    report = ProgramReport(tool="program", name=name, checked=2)
    report.hlo_collectives = synced
    added = list(synced)
    for op in base:
        if op in added:
            added.remove(op)
    if isinstance(allow_added, int):
        over_budget = len(added) > allow_added
    else:
        budget = list(allow_added)
        extra = list(added)
        for op in budget:
            if op in extra:
                extra.remove(op)
        over_budget = bool(extra)
    if over_budget:
        _finding(
            report,
            "added-collectives",
            f"synced step collectives {list(synced)} vs baseline "
            f"{list(base)}: added {added} exceeds the declared budget "
            f"{allow_added!r} — the metric sync no longer rides the "
            "step's existing collectives",
        )
    return set_last_report(report)


# --------------------------------------------------- runtime pin wrappers


def assert_update_transfer_free(metric, args: Sequence[Any], *, warm: int = 6):
    """RUNTIME pin (legacy tier-1 wrapper): after ``warm`` settling
    updates, one more ``update(*args)`` must execute under
    ``jax.transfer_guard("disallow")`` — the dynamic counterpart of
    :func:`verify_metric_update`'s static host-escape check."""
    for _ in range(warm):
        metric.update(*args)
    with jax.transfer_guard("disallow"):
        metric.update(*args)
    return metric


def assert_donated_update_in_place(
    metric,
    args: Sequence[Any],
    state_name: str,
    *,
    warm: int = 3,
    steps: int = 1,
):
    """RUNTIME pin (legacy tier-1 wrapper): with donation enabled, after
    ``warm`` settling updates every one of ``steps`` further updates must
    reuse ``state_name``'s buffer in place (zero realloc), and the final
    one must also be transfer-free."""
    from torcheval_tpu import config

    def _ptr():
        return getattr(metric, state_name).unsafe_buffer_pointer()

    with config.update_donation(True):
        for _ in range(warm):
            metric.update(*args)
        ptr = _ptr()
        for _ in range(max(steps - 1, 0)):
            metric.update(*args)
            assert _ptr() == ptr, (
                f"{type(metric).__name__}.{state_name} was reallocated by "
                "a donated update (zero-realloc contract)"
            )
        with jax.transfer_guard("disallow"):
            metric.update(*args)
        assert _ptr() == ptr, (
            f"{type(metric).__name__}.{state_name} was reallocated by a "
            "donated update (zero-realloc contract)"
        )
    return metric
