"""One-dispatch fused counter accumulation.

Counter metrics' hot loop is ``state += kernel(batch)``. Dispatching the
kernel and each eager add separately costs 3-4 device round-trips per
``update()`` — pure overhead for O(1)-state metrics whose kernels run in
microseconds (the reference hides this inside one torch op stream; on
TPU/JAX, per-dispatch latency dominates instead). This helper jits
``kernel(*dynamic, *config)`` together with the state adds into ONE
compiled program, cached per (kernel, config, arity) so repeated updates
hit the same executable.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

import jax

_CACHE: Dict[Any, Callable] = {}


def fused_accumulate(
    kernel: Callable,
    states: Tuple[jax.Array, ...],
    dynamic: Tuple[jax.Array, ...],
    config: Tuple = (),
) -> Tuple[jax.Array, ...]:
    """``tuple(s + d for s, d in zip(states, kernel(*dynamic, *config)))``
    as one jitted dispatch.

    ``config`` entries must be hashable (they key the cache and are baked
    into the trace as compile-time constants). ``kernel`` may return a
    single array (treated as a 1-tuple) or a tuple matching ``states``.
    """
    key = (kernel, config, len(states), len(dynamic))
    fn = _CACHE.get(key)
    if fn is None:

        def fused(states, *dyn):
            deltas = kernel(*dyn, *config)
            if not isinstance(deltas, tuple):
                deltas = (deltas,)
            if len(deltas) != len(states):
                raise ValueError(
                    f"kernel {kernel.__name__} returned {len(deltas)} deltas "
                    f"for {len(states)} states"
                )
            return tuple(s + d for s, d in zip(states, deltas))

        fn = jax.jit(fused)
        _CACHE[key] = fn
    return fn(states, *dynamic)
