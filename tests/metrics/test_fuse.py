"""fused_accumulate contract: one cached executable per (kernel, config),
correct accumulation, arity mismatch raises."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torcheval_tpu.metrics._fuse import _CACHE, fused_accumulate


def _pair_kernel(x, scale):
    return jnp.sum(x) * scale, jnp.float32(x.shape[0])


def _single_kernel(x):
    return jnp.sum(x)


def test_accumulates_and_caches():
    before = len(_CACHE)
    s = (jnp.zeros(()), jnp.zeros(()))
    x = jnp.arange(4, dtype=jnp.float32)
    s = fused_accumulate(_pair_kernel, s, (x,), (2.0,))
    s = fused_accumulate(_pair_kernel, s, (x,), (2.0,))
    np.testing.assert_allclose(float(s[0]), 2 * 2 * 6.0)
    np.testing.assert_allclose(float(s[1]), 8.0)
    assert len(_CACHE) == before + 1  # second call reused the entry

    # different config -> different cache entry, independent result
    s2 = fused_accumulate(_pair_kernel, (jnp.zeros(()), jnp.zeros(())), (x,), (3.0,))
    np.testing.assert_allclose(float(s2[0]), 18.0)
    assert len(_CACHE) == before + 2


def test_single_delta_kernel():
    (total,) = fused_accumulate(
        _single_kernel, (jnp.float32(1.0),), (jnp.ones(3),)
    )
    np.testing.assert_allclose(float(total), 4.0)


def test_arity_mismatch_raises():
    with pytest.raises(ValueError, match="returned 1 values for 2 states"):
        fused_accumulate(
            _single_kernel, (jnp.zeros(()), jnp.zeros(())), (jnp.ones(3),)
        )


def test_counter_update_is_one_fused_program():
    """The whole point: a counter-metric update routes through ONE cached
    fused executable (kernel + state adds), compiled once for the input
    signature — no separate eager-add programs and no per-update retrace."""
    from torcheval_tpu.metrics import MulticlassF1Score
    from torcheval_tpu.metrics.functional.classification.f1_score import (
        _f1_score_update_jit,
    )

    m = MulticlassF1Score()
    x = jnp.asarray(np.random.default_rng(0).integers(0, 4, 16))
    t = jnp.asarray(np.random.default_rng(1).integers(0, 4, 16))

    # drop any entries earlier tests created so the count below is exact
    for k in [k for k in _CACHE if k[0] is _f1_score_update_jit]:
        del _CACHE[k]

    for _ in range(5):
        m.update(x, t)

    # exactly one fused entry appeared for this metric's (kernel, config)
    new_keys = [k for k in _CACHE if k[0] is _f1_score_update_jit]
    assert len(new_keys) == 1
    fused_fn = _CACHE[new_keys[0]]
    # 5 updates, one trace: the fused program is reused, not rebuilt
    # (_cache_size is jax-private; skip the stronger assert if it goes away)
    if hasattr(fused_fn, "_cache_size"):
        assert fused_fn._cache_size() == 1
