"""Deterministic 32-bit n-gram window mixer.

One left-fold hash shared by BOTH n-gram count planes in the streaming
subsystem: the device fold inside ``StreamingNgramOverlap``'s decode-step
kernel (jax.numpy, uint32 wraparound) and the host mirror inside
``StreamTable``'s per-request stream state (plain python ints). The two
implementations must agree bit-for-bit — the keyed table's finals are
pinned against the standalone metric's counters in the test suite — so
the constants live here, once, and tests/streaming/test_mix.py sweeps
the pair for equality.

The mix itself is a murmur3-finalizer-style avalanche over each token of
the (<= n)-token window, folded left to right from a fixed seed. Token
ids are assumed non-negative int32 (the streaming sentinel for "no token
this step" is -1 and is never hashed). Collisions between distinct
n-grams are expected and harmless for the BLEU-precision core: clipped
matching ``min(candidate_count, reference_count)`` is computed per
bucket, so a collision can only *under*- or *over*-credit by the
colliding mass, bounded by the table width — widen ``buckets`` to
tighten it.
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp

__all__ = ["MIX_SEED", "mix_fold_int", "mix_step_jnp", "mix_seed_jnp"]

# golden-ratio odd multiplier + murmur3-finalizer avalanche constant;
# seed is the FNV-1a 32-bit offset basis. All arithmetic is mod 2^32.
_M1 = 0x9E3779B1
_M2 = 0x85EBCA77
MIX_SEED = 0x811C9DC5
_MASK32 = 0xFFFFFFFF


def mix_fold_int(tokens: Sequence[int], seed: int = MIX_SEED) -> int:
    """Host fold: hash a whole token window with python ints (exact
    uint32 wraparound via masking). Mirror of the device fold below."""
    h = seed & _MASK32
    for tok in tokens:
        h = ((h ^ (int(tok) & _MASK32)) * _M1) & _MASK32
        h ^= h >> 15
        h = (h * _M2) & _MASK32
        h ^= h >> 13
    return h


def mix_seed_jnp() -> jnp.ndarray:
    """The fold seed as a device uint32 scalar."""
    return jnp.uint32(MIX_SEED)


def mix_step_jnp(h: jnp.ndarray, tok: jnp.ndarray) -> jnp.ndarray:
    """Device fold step: absorb one int32 token into a uint32 hash.
    uint32 multiply wraps in XLA, matching the masked host fold."""
    h = (h ^ tok.astype(jnp.uint32)) * jnp.uint32(_M1)
    h = h ^ (h >> jnp.uint32(15))
    h = h * jnp.uint32(_M2)
    return h ^ (h >> jnp.uint32(13))
