"""torcheval_tpu: a TPU-native model-evaluation metrics framework.

A ground-up JAX/XLA re-design of the reference metrics library's capability
surface (see SURVEY.md): class metrics with update/compute/merge_state/reset
deferred semantics over device-resident state, their stateless functional
siblings as jitted XLA kernels, and a distributed sync toolkit that lowers
state merges to XLA collectives over ICI/DCN — including an in-jit path
(``torcheval_tpu.metrics.sharded``) that fuses metric sync into the training
step itself. See ``torcheval_tpu.metrics.__all__`` for the currently
implemented metric inventory.
"""

from torcheval_tpu.version import __version__

__all__ = ["__version__"]
