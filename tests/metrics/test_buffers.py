"""Fixed-shape growable buffer layer tests (metrics/_buffer.py).

Pins the round-2 design goals from SURVEY §7: power-of-2 preallocated device
buffers with valid-count masking, so O(n) example-buffering metrics compile
O(log n) XLA programs across arbitrarily many updates (the reference's
list-append pattern — reference classification/auroc.py:87-89 — recompiles
per distinct total length).
"""

from __future__ import annotations

import numpy as np
import pytest
import sklearn.metrics as skm
import jax.numpy as jnp

from torcheval_tpu.metrics import (
    AUC,
    BinaryAUPRC,
    BinaryAUROC,
    BinaryPrecisionRecallCurve,
    Cat,
    MulticlassAUROC,
)
from torcheval_tpu.metrics._buffer import MIN_CAPACITY, _write_all, next_capacity
from torcheval_tpu.metrics.functional.classification.auroc import (
    _binary_auroc_compute_jit,
)
from torcheval_tpu.metrics.toolkit import sync_and_compute
from torcheval_tpu.distributed import LocalReplicaGroup

RNG = np.random.default_rng(7)


def test_next_capacity():
    assert next_capacity(1) == MIN_CAPACITY
    assert next_capacity(MIN_CAPACITY) == MIN_CAPACITY
    assert next_capacity(MIN_CAPACITY + 1) == 2 * MIN_CAPACITY
    assert next_capacity(1000) == 1024
    assert next_capacity(1024) == 1024
    assert next_capacity(1025) == 2048


def test_update_compiles_o_log_n():
    """100 growing updates must stay within the O(log n) compile budget."""
    batch = 37
    writes_before = _write_all._cache_size()
    computes_before = _binary_auroc_compute_jit._cache_size()

    m = BinaryAUROC()
    for i in range(100):
        x = RNG.random(batch).astype(np.float32)
        t = (RNG.random(batch) < 0.5).astype(np.float32)
        m.update(jnp.asarray(x), jnp.asarray(t))
        if i % 10 == 0:
            m.compute()

    assert m.num_samples == 100 * batch
    # distinct capacities touched: 64..4096 -> 7; one write program per
    # (capacity, batch-shape) pair, covering ALL buffers of the metric
    assert _write_all._cache_size() - writes_before <= 8
    # compute kernel compiles once per capacity, NOT per count
    assert _binary_auroc_compute_jit._cache_size() - computes_before <= 8


def test_buffer_growth_preserves_values():
    m = BinaryAUROC()
    xs, ts = [], []
    for batch in (5, MIN_CAPACITY, 200, 1):  # crosses two growth boundaries
        x = RNG.random(batch).astype(np.float32)
        t = (RNG.random(batch) < 0.4).astype(np.float32)
        xs.append(x)
        ts.append(t)
        m.update(jnp.asarray(x), jnp.asarray(t))
    x_all, t_all = np.concatenate(xs), np.concatenate(ts)
    assert m.num_samples == x_all.size
    expected = skm.roc_auc_score(t_all, x_all)
    np.testing.assert_allclose(float(m.compute()), expected, atol=1e-5)


def test_padding_is_neutral_at_every_count():
    """Results at non-power-of-2 counts equal unpadded oracles."""
    x = RNG.random(147).astype(np.float32)
    t = (RNG.random(147) < 0.5).astype(np.float32)

    auroc, auprc, prc = BinaryAUROC(), BinaryAUPRC(), BinaryPrecisionRecallCurve()
    for m in (auroc, auprc, prc):
        m.update(jnp.asarray(x), jnp.asarray(t))
    np.testing.assert_allclose(
        float(auroc.compute()), skm.roc_auc_score(t, x), atol=1e-5
    )
    np.testing.assert_allclose(
        float(auprc.compute()), skm.average_precision_score(t, x), atol=1e-5
    )
    p, r, th = prc.compute()
    rp, rr, rt = skm.precision_recall_curve(t, x)
    np.testing.assert_allclose(np.asarray(p), rp, atol=1e-6)
    np.testing.assert_allclose(np.asarray(r), rr, atol=1e-6)
    np.testing.assert_allclose(np.asarray(th), rt, atol=1e-6)


def test_multiclass_auroc_mask():
    x = RNG.random((83, 5)).astype(np.float32)
    x /= x.sum(axis=1, keepdims=True)
    t = RNG.integers(0, 5, 83)
    m = MulticlassAUROC(num_classes=5)
    m.update(jnp.asarray(x[:40]), jnp.asarray(t[:40]))
    m.update(jnp.asarray(x[40:]), jnp.asarray(t[40:]))
    expected = skm.roc_auc_score(
        t, x, multi_class="ovr", average="macro", labels=list(range(5))
    )
    np.testing.assert_allclose(float(m.compute()), expected, atol=1e-5)


def test_merge_asymmetric_and_empty():
    x1 = RNG.random(31).astype(np.float32)
    t1 = (RNG.random(31) < 0.5).astype(np.float32)
    x2 = RNG.random(97).astype(np.float32)
    t2 = (RNG.random(97) < 0.5).astype(np.float32)

    a, b, empty = BinaryAUROC(), BinaryAUROC(), BinaryAUROC()
    a.update(jnp.asarray(x1), jnp.asarray(t1))
    b.update(jnp.asarray(x2), jnp.asarray(t2))
    a.merge_state([b, empty])
    assert a.num_samples == 128
    expected = skm.roc_auc_score(
        np.concatenate([t1, t2]), np.concatenate([x1, x2])
    )
    np.testing.assert_allclose(float(a.compute()), expected, atol=1e-5)

    # merging INTO an empty metric adopts peer data
    c = BinaryAUROC()
    peer = BinaryAUROC()
    peer.update(jnp.asarray(x1), jnp.asarray(t1))
    c.merge_state([peer])
    np.testing.assert_allclose(
        float(c.compute()), skm.roc_auc_score(t1, x1), atol=1e-5
    )
    # peers unchanged
    assert peer.num_samples == 31


def test_state_dict_roundtrip_preserves_buffer():
    m = BinaryAUROC()
    x = RNG.random(70).astype(np.float32)
    t = (RNG.random(70) < 0.5).astype(np.float32)
    m.update(jnp.asarray(x), jnp.asarray(t))
    sd = m.state_dict()
    assert sd["_num_samples"] == 70
    fresh = BinaryAUROC()
    fresh.load_state_dict(sd)
    np.testing.assert_allclose(
        float(fresh.compute()), float(m.compute()), atol=1e-7
    )
    # restored metric keeps growing correctly
    fresh.update(jnp.asarray(x), jnp.asarray(t))
    assert fresh.num_samples == 140


def test_toolkit_sync_buffered_ragged_counts():
    """Eager toolkit sync over replicas with different (and zero) counts."""
    datas = [(31, 0.3), (5, 0.7), (0, 0.0)]
    replicas, all_x, all_t = [], [], []
    for n, p in datas:
        m = BinaryAUPRC()
        if n:
            x = RNG.random(n).astype(np.float32)
            t = (RNG.random(n) < p).astype(np.float32)
            m.update(jnp.asarray(x), jnp.asarray(t))
            all_x.append(x)
            all_t.append(t)
        replicas.append(m)
    import jax

    group = LocalReplicaGroup(devices=jax.devices("cpu")[: len(replicas)])
    result = sync_and_compute(replicas, group)
    expected = skm.average_precision_score(
        np.concatenate(all_t), np.concatenate(all_x)
    )
    np.testing.assert_allclose(float(result), expected, atol=1e-5)


def test_cat_and_auc_growth():
    cat = Cat(dim=1)
    a = np.arange(6, dtype=np.float32).reshape(2, 3)
    b = np.arange(6, 10, dtype=np.float32).reshape(2, 2)
    cat.update(jnp.asarray(a))
    cat.update(jnp.asarray(b))
    np.testing.assert_array_equal(
        np.asarray(cat.compute()), np.concatenate([a, b], axis=1)
    )

    auc = AUC()
    auc.update(jnp.asarray([0.0, 1.0]), jnp.asarray([1.0, 1.0]))
    auc.update(jnp.asarray([2.0]), jnp.asarray([1.0]))
    np.testing.assert_allclose(np.asarray(auc.compute()), [2.0], atol=1e-6)

    # unsorted x with reorder=True across growth boundary
    auc2 = AUC(reorder=True)
    xs = RNG.permutation(np.linspace(0, 1, 100)).astype(np.float32)
    ys = np.ones(100, dtype=np.float32)
    auc2.update(jnp.asarray(xs[:70]), jnp.asarray(ys[:70]))
    auc2.update(jnp.asarray(xs[70:]), jnp.asarray(ys[70:]))
    np.testing.assert_allclose(np.asarray(auc2.compute()), [1.0], atol=1e-5)


def test_snapshot_survives_donated_appends():
    """state_dict snapshots must stay valid across later updates: the append
    kernel donates the live buffer, so snapshots must be real copies."""
    m = BinaryAUROC()
    x = RNG.random(40).astype(np.float32)
    t = (RNG.random(40) < 0.5).astype(np.float32)
    m.update(jnp.asarray(x), jnp.asarray(t))
    snap = m.state_dict()
    before = float(m.compute())
    # several more appends into the same capacity-64 buffer (donated writes)
    for _ in range(3):
        m.update(jnp.asarray(x[:8]), jnp.asarray(t[:8]))
    # the snapshot's arrays are still alive and unchanged
    fresh = BinaryAUROC()
    fresh.load_state_dict(snap)
    np.testing.assert_allclose(float(fresh.compute()), before, atol=1e-7)
    # and a load_state_dict'ed metric does not invalidate the caller's dict
    fresh.update(jnp.asarray(x[:8]), jnp.asarray(t[:8]))
    np.testing.assert_array_equal(
        np.asarray(snap["inputs"]).shape[-1], 64
    )


def test_compute_before_update_raises():
    with pytest.raises(RuntimeError, match="has no data"):
        BinaryAUROC().compute()
    with pytest.raises(RuntimeError, match="has no data"):
        MulticlassAUROC(num_classes=3).compute()
