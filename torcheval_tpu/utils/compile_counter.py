"""XLA compile-count observability.

The retrace-proofing work (shape bucketing, ``metrics/_bucket.py``) makes a
claim — "a ragged eval stream compiles O(log max_batch) programs" — that is
invisible without instrumentation: a silent recompile costs tens of ms to
seconds but produces correct numbers. :class:`CompileCounter` turns compile
activity into an assertable quantity by listening to JAX's monitoring
events:

- ``/jax/core/compile/backend_compile_duration`` — this event wraps
  ``compiler.compile_or_get_cached`` (jax pxla), so one record fires per
  PROGRAM DEMAND: a fresh backend compile or a persistent-cache load
  alike. That makes it exactly the quantity the bucket bound limits, warm
  or cold cache (``programs``).
- ``/jax/compilation_cache/cache_hits`` — how many of those demands were
  served from the persistent compilation cache; ``compiles`` (the
  demands that actually paid the compiler) is the difference.

Used by ``bench.py``'s ``variable_batch`` config and
``tests/metrics/test_retrace_guard.py``; available to users to audit their
own eval loops (docs/variable-shape-eval.md).

:func:`enable_persistent_compilation_cache` is the companion knob: with a
cache directory configured, the bucket set survives process restarts, so a
re-run of the same eval pipeline pays ZERO backend compiles.
"""

from __future__ import annotations

import os
from typing import List, Optional

import jax

BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"

# jax.monitoring offers registration but (in this JAX generation) no public
# per-listener removal, so ONE module-level listener is registered lazily
# and fans out to whichever counters are currently active.
_ACTIVE: List["CompileCounter"] = []
_INSTALLED = False

# Event sinks: callables ``sink(what, seconds)`` with ``what`` one of
# "compile" (a program demand; seconds = time inside compile-or-load) or
# "cache_hit" (a persistent-cache hit; seconds = 0). The observability
# recorder (torcheval_tpu.obs) registers one to turn compile activity
# into timestamped CompileEvents; sinks must be cheap and non-raising.
_EVENT_SINKS: List = []


def add_event_sink(sink) -> None:
    """Register a compile-activity sink (see ``_EVENT_SINKS``)."""
    _install()  # sinks need the jax.monitoring listeners live
    if sink not in _EVENT_SINKS:
        _EVENT_SINKS.append(sink)


def remove_event_sink(sink) -> None:
    if sink in _EVENT_SINKS:
        _EVENT_SINKS.remove(sink)


def _on_duration(event: str, duration: float, **_kwargs) -> None:
    if event == BACKEND_COMPILE_EVENT:
        for counter in _ACTIVE:
            counter._programs += 1
            counter._compile_secs += duration
        for sink in _EVENT_SINKS:
            sink("compile", duration)


def _on_event(event: str, **_kwargs) -> None:
    if event == CACHE_HIT_EVENT:
        for counter in _ACTIVE:
            counter._cache_hits += 1
        for sink in _EVENT_SINKS:
            sink("cache_hit", 0.0)


def _install() -> None:
    global _INSTALLED
    if _INSTALLED:
        return
    jax.monitoring.register_event_duration_secs_listener(_on_duration)
    jax.monitoring.register_event_listener(_on_event)
    _INSTALLED = True


class CompileCounter:
    """Counts XLA program demands (compiles / cache loads) within a
    ``with`` block.

    >>> from torcheval_tpu.utils import CompileCounter
    >>> with CompileCounter() as cc:
    ...     for batch in loader:
    ...         metric.update(batch.scores, batch.labels)
    >>> cc.programs          # programs demanded (compiled OR cache-loaded)
    >>> cc.compiles          # of which actually paid the backend compiler
    >>> cc.cache_hits        # of which replayed from the persistent cache
    >>> cc.compile_secs      # wall seconds inside compile-or-load

    Counts are process-wide (any JAX computation compiling inside the block
    is counted), which is the point: a retrace anywhere in the update path
    shows up here. Reentrant/nested counters each see every event.
    """

    def __init__(self) -> None:
        self._programs = 0
        self._cache_hits = 0
        self._compile_secs = 0.0

    # ------------------------------------------------------------- results

    @property
    def programs(self) -> int:
        """Distinct programs demanded — fresh compiles AND persistent-cache
        loads. The quantity the bucket bound is asserted against: a warm
        persistent cache must not make a retrace regression invisible."""
        return self._programs

    @property
    def compiles(self) -> int:
        """Demands that actually paid the backend compiler."""
        return max(0, self._programs - self._cache_hits)

    @property
    def cache_hits(self) -> int:
        return self._cache_hits

    @property
    def compile_secs(self) -> float:
        return self._compile_secs

    def reset(self) -> None:
        self._programs = 0
        self._cache_hits = 0
        self._compile_secs = 0.0

    # ------------------------------------------------------------- context

    def __enter__(self) -> "CompileCounter":
        _install()
        _ACTIVE.append(self)
        return self

    def __exit__(self, *exc_info) -> None:
        _ACTIVE.remove(self)


def enable_persistent_compilation_cache(
    cache_dir: Optional[str] = None,
    *,
    min_compile_time_secs: float = 1.0,
) -> str:
    """Opt into JAX's persistent compilation cache so the bucket set
    survives process restarts.

    With shape bucketing the compiled-program set is finite
    (O(log max_batch) per metric); persisting it means a restarted eval
    pipeline replays every program from disk instead of re-tracing —
    ``CompileCounter.cache_hits`` counts the replays.

    Args:
        cache_dir: cache directory. Defaults to ``$JAX_COMPILATION_CACHE_DIR``
            or ``~/.cache/torcheval_tpu/xla_cache``. Created if missing.
        min_compile_time_secs: only compiles at least this expensive are
            persisted (JAX's knob; 0 persists everything, including the
            trivial pads that are cheaper to re-trace than to read back).

    Returns the cache directory in use.
    """
    if cache_dir is None:
        cache_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR") or os.path.join(
            os.path.expanduser("~"), ".cache", "torcheval_tpu", "xla_cache"
        )
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update(
        "jax_persistent_cache_min_compile_time_secs", float(min_compile_time_secs)
    )
    return cache_dir
