"""Aggregation metric tests (AUC/Cat/Max/Mean/Min/Sum/Throughput) vs the
reference oracle, via the shared MetricClassTester harness."""

import jax.numpy as jnp
import numpy as np
import pytest
import torch

from tests.ref_oracle import load_reference_metrics
from torcheval_tpu.metrics import AUC, Cat, Max, Mean, Min, Sum, Throughput
from torcheval_tpu.metrics import functional as F
from torcheval_tpu.utils.test_utils.metric_class_tester import (
    MetricClassTester,
    assert_result_close,
)

REF_M, REF_F = load_reference_metrics()
RNG = np.random.default_rng(42)


class TestSum(MetricClassTester):
    def test_sum_class(self):
        inputs = [RNG.normal(size=(5,)).astype(np.float32) for _ in range(8)]
        expected = REF_M.Sum().update(torch.tensor(np.concatenate(inputs))).compute()
        self.run_class_implementation_tests(
            metric=Sum(),
            state_names={"weighted_sum"},
            update_kwargs={"input": inputs},
            compute_result=np.asarray(expected),
        )

    def test_sum_weighted(self):
        x = RNG.normal(size=(6,)).astype(np.float32)
        w = RNG.uniform(size=(6,)).astype(np.float32)
        ours = F.sum(jnp.asarray(x), jnp.asarray(w))
        ref = REF_F.sum(torch.tensor(x), torch.tensor(w))
        assert_result_close(ours, np.asarray(ref))
        assert_result_close(F.sum(jnp.asarray(x), 2), np.asarray(REF_F.sum(torch.tensor(x), 2)))

    def test_sum_weight_shape_mismatch_raises(self):
        with pytest.raises(ValueError, match="Weight must be"):
            F.sum(jnp.ones(3), jnp.ones(4))


class TestMean(MetricClassTester):
    def test_mean_class(self):
        inputs = [RNG.normal(size=(4,)).astype(np.float32) for _ in range(8)]
        weights = [RNG.uniform(0.1, 1.0, size=(4,)).astype(np.float32) for _ in range(8)]
        ref = REF_M.Mean()
        for x, w in zip(inputs, weights):
            ref.update(torch.tensor(x), weight=torch.tensor(w))
        self.run_class_implementation_tests(
            metric=Mean(),
            state_names={"weighted_sum", "weights"},
            update_kwargs={
                "input": inputs,
                "weight": [jnp.asarray(w) for w in weights],
            },
            compute_result=np.asarray(ref.compute()),
        )

    def test_mean_functional_scalar_weight(self):
        x = RNG.normal(size=(7,)).astype(np.float32)
        assert_result_close(
            F.mean(jnp.asarray(x), 0.3),
            np.asarray(REF_F.mean(torch.tensor(x), 0.3)),
        )


class TestMaxMin(MetricClassTester):
    def test_max_class(self):
        inputs = [RNG.normal(size=(3, 2)).astype(np.float32) for _ in range(8)]
        self.run_class_implementation_tests(
            metric=Max(),
            state_names={"max"},
            update_kwargs={"input": inputs},
            compute_result=np.max(np.stack(inputs)),
        )

    def test_min_class(self):
        inputs = [RNG.normal(size=(5,)).astype(np.float32) for _ in range(8)]
        self.run_class_implementation_tests(
            metric=Min(),
            state_names={"min"},
            update_kwargs={"input": inputs},
            compute_result=np.min(np.stack(inputs)),
        )


class TestCat(MetricClassTester):
    def test_cat_class(self):
        inputs = [RNG.normal(size=(2, 3)).astype(np.float32) for _ in range(8)]
        self.run_class_implementation_tests(
            metric=Cat(),
            state_names={"dim", "inputs", "_num_samples"},
            update_kwargs={"input": inputs},
            compute_result=np.concatenate(inputs, axis=0),
        )

    def test_cat_empty(self):
        assert Cat().compute().size == 0

    def test_cat_dim1(self):
        m = Cat(dim=1)
        m.update(jnp.ones((2, 2))).update(jnp.zeros((2, 1)))
        assert m.compute().shape == (2, 3)


class TestAUC(MetricClassTester):
    def test_auc_class_vs_reference(self):
        xs = [np.sort(RNG.uniform(size=(4,))).astype(np.float32) for _ in range(8)]
        ys = [RNG.uniform(size=(4,)).astype(np.float32) for _ in range(8)]
        ref = REF_M.AUC()
        for x, y in zip(xs, ys):
            ref.update(torch.tensor(x), torch.tensor(y))
        self.run_class_implementation_tests(
            metric=AUC(),
            state_names={"x", "y", "_num_samples"},
            update_kwargs={"x": xs, "y": ys},
            compute_result=np.asarray(ref.compute()),
            atol=1e-4,
        )

    def test_auc_functional(self):
        x = np.sort(RNG.uniform(size=(6,))).astype(np.float32)
        y = RNG.uniform(size=(6,)).astype(np.float32)
        assert_result_close(
            F.auc(jnp.asarray(x), jnp.asarray(y)),
            np.asarray(REF_F.auc(torch.tensor(x), torch.tensor(y))).reshape(-1),
            atol=1e-5,
        )

    def test_auc_shape_mismatch_raises(self):
        with pytest.raises(ValueError, match="same shape"):
            F.auc(jnp.ones(3), jnp.ones(4))


class TestThroughput(MetricClassTester):
    def test_throughput_class(self):
        nums = [64, 32, 128, 64, 16, 64, 32, 64]
        times = [2.0, 1.0, 4.0, 2.0, 0.5, 2.0, 1.0, 2.0]
        # merge across ranks: sum(items) / max(per-rank summed elapsed)
        per_rank_elapsed = [sum(times[r * 2 : (r + 1) * 2]) for r in range(4)]
        merge_expected = sum(nums) / max(per_rank_elapsed)
        self.run_class_implementation_tests(
            metric=Throughput(),
            state_names={"num_total", "elapsed_time_sec"},
            update_kwargs={"num_processed": nums, "elapsed_time_sec": times},
            compute_result=sum(nums) / sum(times),
            merge_and_compute_result=merge_expected,
        )

    def test_throughput_functional(self):
        assert F.throughput(64, 2.0) == 32.0
        with pytest.raises(ValueError, match="non-negative"):
            F.throughput(-1, 1.0)
        with pytest.raises(ValueError, match="positive"):
            F.throughput(5, 0.0)

    def test_throughput_no_update_warns(self):
        assert Throughput().compute() == 0.0
