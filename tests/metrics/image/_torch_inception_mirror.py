"""Independent torch mirror of torchvision's InceptionV3 trunk.

torchvision is absent in this image, so the published pooled-feature FID
parity cannot be checked against it directly (reference
torcheval/metrics/image/fid.py:28-50 defines FID by torchvision's
pretrained features). This module closes the wiring gap (VERDICT r3
missing item 1) with an INDEPENDENT re-implementation of the published
torchvision ``inception_v3`` architecture in plain torch:

- module attribute names reproduce torchvision's state-dict naming exactly
  (``Mixed_5b.branch5x5_1.conv.weight``, ...), so a synthesized state dict
  round-trips through ``load_torchvision_inception_params`` the same way a
  real pretrained one would;
- the forward returns every Mixed block's activation plus the 2048-d
  pooled features, so the Flax port is checked block-by-block, not just at
  one probed conv (what round 3 had);
- torch's conv/bn/pool are an independent implementation of the math, so
  numerical agreement validates stride/padding/layout/eps semantics, not
  just plumbing.

Weights are deterministic random (He-scaled convs, normalized-ish batch
stats) — FID wiring parity is weight-agnostic: any wrong branch order,
stride, padding, or pooling breaks agreement for ANY weights.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Dict

import numpy as np
import torch
import torch.nn.functional as F
from torch import nn


class BasicConv2d(nn.Module):
    """conv(no bias) -> batchnorm(eps=0.001) -> relu."""

    def __init__(self, in_channels: int, out_channels: int, **conv_kwargs):
        super().__init__()
        self.conv = nn.Conv2d(
            in_channels, out_channels, bias=False, **conv_kwargs
        )
        self.bn = nn.BatchNorm2d(out_channels, eps=0.001)

    def forward(self, x):
        return F.relu(self.bn(self.conv(x)))


class InceptionA(nn.Module):
    def __init__(self, in_channels: int, pool_features: int):
        super().__init__()
        self.branch1x1 = BasicConv2d(in_channels, 64, kernel_size=1)
        self.branch5x5_1 = BasicConv2d(in_channels, 48, kernel_size=1)
        self.branch5x5_2 = BasicConv2d(48, 64, kernel_size=5, padding=2)
        self.branch3x3dbl_1 = BasicConv2d(in_channels, 64, kernel_size=1)
        self.branch3x3dbl_2 = BasicConv2d(64, 96, kernel_size=3, padding=1)
        self.branch3x3dbl_3 = BasicConv2d(96, 96, kernel_size=3, padding=1)
        self.branch_pool = BasicConv2d(
            in_channels, pool_features, kernel_size=1
        )

    def forward(self, x):
        b1 = self.branch1x1(x)
        b5 = self.branch5x5_2(self.branch5x5_1(x))
        b3 = self.branch3x3dbl_3(self.branch3x3dbl_2(self.branch3x3dbl_1(x)))
        bp = self.branch_pool(F.avg_pool2d(x, 3, stride=1, padding=1))
        return torch.cat([b1, b5, b3, bp], 1)


class InceptionB(nn.Module):
    def __init__(self, in_channels: int):
        super().__init__()
        self.branch3x3 = BasicConv2d(in_channels, 384, kernel_size=3, stride=2)
        self.branch3x3dbl_1 = BasicConv2d(in_channels, 64, kernel_size=1)
        self.branch3x3dbl_2 = BasicConv2d(64, 96, kernel_size=3, padding=1)
        self.branch3x3dbl_3 = BasicConv2d(96, 96, kernel_size=3, stride=2)

    def forward(self, x):
        b3 = self.branch3x3(x)
        bd = self.branch3x3dbl_3(self.branch3x3dbl_2(self.branch3x3dbl_1(x)))
        bp = F.max_pool2d(x, 3, stride=2)
        return torch.cat([b3, bd, bp], 1)


class InceptionC(nn.Module):
    def __init__(self, in_channels: int, channels_7x7: int):
        super().__init__()
        c7 = channels_7x7
        self.branch1x1 = BasicConv2d(in_channels, 192, kernel_size=1)
        self.branch7x7_1 = BasicConv2d(in_channels, c7, kernel_size=1)
        self.branch7x7_2 = BasicConv2d(
            c7, c7, kernel_size=(1, 7), padding=(0, 3)
        )
        self.branch7x7_3 = BasicConv2d(
            c7, 192, kernel_size=(7, 1), padding=(3, 0)
        )
        self.branch7x7dbl_1 = BasicConv2d(in_channels, c7, kernel_size=1)
        self.branch7x7dbl_2 = BasicConv2d(
            c7, c7, kernel_size=(7, 1), padding=(3, 0)
        )
        self.branch7x7dbl_3 = BasicConv2d(
            c7, c7, kernel_size=(1, 7), padding=(0, 3)
        )
        self.branch7x7dbl_4 = BasicConv2d(
            c7, c7, kernel_size=(7, 1), padding=(3, 0)
        )
        self.branch7x7dbl_5 = BasicConv2d(
            c7, 192, kernel_size=(1, 7), padding=(0, 3)
        )
        self.branch_pool = BasicConv2d(in_channels, 192, kernel_size=1)

    def forward(self, x):
        b1 = self.branch1x1(x)
        b7 = self.branch7x7_3(self.branch7x7_2(self.branch7x7_1(x)))
        bd = self.branch7x7dbl_5(
            self.branch7x7dbl_4(
                self.branch7x7dbl_3(
                    self.branch7x7dbl_2(self.branch7x7dbl_1(x))
                )
            )
        )
        bp = self.branch_pool(F.avg_pool2d(x, 3, stride=1, padding=1))
        return torch.cat([b1, b7, bd, bp], 1)


class InceptionD(nn.Module):
    def __init__(self, in_channels: int):
        super().__init__()
        self.branch3x3_1 = BasicConv2d(in_channels, 192, kernel_size=1)
        self.branch3x3_2 = BasicConv2d(192, 320, kernel_size=3, stride=2)
        self.branch7x7x3_1 = BasicConv2d(in_channels, 192, kernel_size=1)
        self.branch7x7x3_2 = BasicConv2d(
            192, 192, kernel_size=(1, 7), padding=(0, 3)
        )
        self.branch7x7x3_3 = BasicConv2d(
            192, 192, kernel_size=(7, 1), padding=(3, 0)
        )
        self.branch7x7x3_4 = BasicConv2d(192, 192, kernel_size=3, stride=2)

    def forward(self, x):
        b3 = self.branch3x3_2(self.branch3x3_1(x))
        b7 = self.branch7x7x3_4(
            self.branch7x7x3_3(self.branch7x7x3_2(self.branch7x7x3_1(x)))
        )
        bp = F.max_pool2d(x, 3, stride=2)
        return torch.cat([b3, b7, bp], 1)


class InceptionE(nn.Module):
    def __init__(self, in_channels: int):
        super().__init__()
        self.branch1x1 = BasicConv2d(in_channels, 320, kernel_size=1)
        self.branch3x3_1 = BasicConv2d(in_channels, 384, kernel_size=1)
        self.branch3x3_2a = BasicConv2d(
            384, 384, kernel_size=(1, 3), padding=(0, 1)
        )
        self.branch3x3_2b = BasicConv2d(
            384, 384, kernel_size=(3, 1), padding=(1, 0)
        )
        self.branch3x3dbl_1 = BasicConv2d(in_channels, 448, kernel_size=1)
        self.branch3x3dbl_2 = BasicConv2d(448, 384, kernel_size=3, padding=1)
        self.branch3x3dbl_3a = BasicConv2d(
            384, 384, kernel_size=(1, 3), padding=(0, 1)
        )
        self.branch3x3dbl_3b = BasicConv2d(
            384, 384, kernel_size=(3, 1), padding=(1, 0)
        )
        self.branch_pool = BasicConv2d(in_channels, 192, kernel_size=1)

    def forward(self, x):
        b1 = self.branch1x1(x)
        b3 = self.branch3x3_1(x)
        b3 = torch.cat([self.branch3x3_2a(b3), self.branch3x3_2b(b3)], 1)
        bd = self.branch3x3dbl_2(self.branch3x3dbl_1(x))
        bd = torch.cat([self.branch3x3dbl_3a(bd), self.branch3x3dbl_3b(bd)], 1)
        bp = self.branch_pool(F.avg_pool2d(x, 3, stride=1, padding=1))
        return torch.cat([b1, b3, bd, bp], 1)


class TorchInceptionV3Mirror(nn.Module):
    """The trunk (fc removed, no aux head), NCHW, 299x299 [0,1] input.

    ``forward`` returns an ordered ``{checkpoint: activation}`` dict —
    every Mixed block plus the final ``pool`` (N, 2048).
    """

    def __init__(self, transform_input: bool = True):
        super().__init__()
        self.transform_input = transform_input
        self.Conv2d_1a_3x3 = BasicConv2d(3, 32, kernel_size=3, stride=2)
        self.Conv2d_2a_3x3 = BasicConv2d(32, 32, kernel_size=3)
        self.Conv2d_2b_3x3 = BasicConv2d(32, 64, kernel_size=3, padding=1)
        self.Conv2d_3b_1x1 = BasicConv2d(64, 80, kernel_size=1)
        self.Conv2d_4a_3x3 = BasicConv2d(80, 192, kernel_size=3)
        self.Mixed_5b = InceptionA(192, pool_features=32)
        self.Mixed_5c = InceptionA(256, pool_features=64)
        self.Mixed_5d = InceptionA(288, pool_features=64)
        self.Mixed_6a = InceptionB(288)
        self.Mixed_6b = InceptionC(768, channels_7x7=128)
        self.Mixed_6c = InceptionC(768, channels_7x7=160)
        self.Mixed_6d = InceptionC(768, channels_7x7=160)
        self.Mixed_6e = InceptionC(768, channels_7x7=192)
        self.Mixed_7a = InceptionD(768)
        self.Mixed_7b = InceptionE(1280)
        self.Mixed_7c = InceptionE(2048)

    def forward(self, x) -> "OrderedDict[str, torch.Tensor]":
        if self.transform_input:
            ch0 = x[:, 0:1] * (0.229 / 0.5) + (0.485 - 0.5) / 0.5
            ch1 = x[:, 1:2] * (0.224 / 0.5) + (0.456 - 0.5) / 0.5
            ch2 = x[:, 2:3] * (0.225 / 0.5) + (0.406 - 0.5) / 0.5
            x = torch.cat([ch0, ch1, ch2], 1)
        out: "OrderedDict[str, torch.Tensor]" = OrderedDict()
        x = self.Conv2d_1a_3x3(x)
        x = self.Conv2d_2a_3x3(x)
        x = self.Conv2d_2b_3x3(x)
        x = F.max_pool2d(x, 3, stride=2)
        x = self.Conv2d_3b_1x1(x)
        x = self.Conv2d_4a_3x3(x)
        x = F.max_pool2d(x, 3, stride=2)
        for name in (
            "Mixed_5b", "Mixed_5c", "Mixed_5d",
            "Mixed_6a", "Mixed_6b", "Mixed_6c", "Mixed_6d", "Mixed_6e",
            "Mixed_7a", "Mixed_7b", "Mixed_7c",
        ):
            x = getattr(self, name)(x)
            out[name] = x
        out["pool"] = F.adaptive_avg_pool2d(x, (1, 1)).flatten(1)
        return out


def synth_torchvision_state_dict(seed: int = 0) -> Dict[str, np.ndarray]:
    """Deterministic random weights in torchvision state-dict format.

    He-scaled conv kernels and normalized-ish batch stats keep activation
    magnitudes O(1) through all 17 conv levels, so per-block comparisons
    stay numerically meaningful at f32.
    """
    mirror = TorchInceptionV3Mirror()
    rng = np.random.default_rng(seed)
    state: Dict[str, np.ndarray] = {}
    for name, param in sorted(mirror.state_dict().items()):
        shape = tuple(param.shape)
        if name.endswith("num_batches_tracked"):
            continue
        if name.endswith("bn.running_var"):
            value = rng.uniform(0.5, 1.5, size=shape)
        elif name.endswith("bn.running_mean"):
            value = rng.normal(0.0, 0.1, size=shape)
        elif name.endswith("bn.weight"):
            value = rng.uniform(0.5, 1.5, size=shape)
        elif name.endswith("bn.bias"):
            value = rng.normal(0.0, 0.1, size=shape)
        else:  # conv kernel, OIHW
            fan_in = int(np.prod(shape[1:]))
            value = rng.normal(0.0, (2.0 / fan_in) ** 0.5, size=shape)
        state[name] = value.astype(np.float32)
    return state


def run_mirror(
    state_dict: Dict[str, np.ndarray], images_nchw: np.ndarray
) -> "OrderedDict[str, np.ndarray]":
    """Load ``state_dict`` into the mirror and run it in eval mode."""
    mirror = TorchInceptionV3Mirror()
    mirror.load_state_dict(
        {k: torch.tensor(v) for k, v in state_dict.items()}, strict=False
    )
    mirror.eval()
    with torch.no_grad():
        acts = mirror(torch.tensor(images_nchw))
    return OrderedDict((k, v.numpy()) for k, v in acts.items())
