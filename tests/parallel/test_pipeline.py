"""GPipe-style pipeline over a virtual pp mesh equals applying the stages
sequentially, for varying stage/microbatch counts, with grads, and with
in-pipeline metric counter accumulation."""

from functools import partial

import numpy as np
import pytest

import jax
import jax.numpy as jnp
try:
    from jax import shard_map
except ImportError:  # pre-0.4.38 jax keeps it under experimental
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from torcheval_tpu.parallel import pipeline_apply, pipeline_reference

RNG = np.random.default_rng(23)

MB, DIM = 4, 16  # microbatch rows, feature width


def _stage_fn(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def _stacked_params(n_stages):
    return {
        "w": jnp.asarray(
            RNG.normal(size=(n_stages, DIM, DIM)) * 0.5, jnp.float32
        ),
        "b": jnp.asarray(RNG.normal(size=(n_stages, DIM)) * 0.1, jnp.float32),
    }


def _mesh(n):
    return Mesh(np.array(jax.devices("cpu")[:n]), ("pp",))


def _pipelined(mesh):
    @jax.jit
    @partial(
        shard_map, mesh=mesh, in_specs=(P("pp"), P()), out_specs=P()
    )
    def run(stacked, x):
        local = jax.tree_util.tree_map(lambda a: a[0], stacked)
        return pipeline_apply(_stage_fn, local, x, axis_name="pp")

    return run


@pytest.mark.parametrize("n_stages", [2, 4, 8])
@pytest.mark.parametrize("n_micro", [1, 3, 8])
def test_pipeline_matches_sequential(n_stages, n_micro):
    params = _stacked_params(n_stages)
    x = jnp.asarray(RNG.normal(size=(n_micro, MB, DIM)), jnp.float32)
    out = _pipelined(_mesh(n_stages))(params, x)
    expected = pipeline_reference(_stage_fn, params, x)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(expected), atol=1e-6, rtol=1e-6
    )


@pytest.mark.slow
def test_pipeline_grads_flow():
    """Differentiable through the schedule (training-step compatibility)."""
    n_stages, n_micro = 4, 6
    params = _stacked_params(n_stages)
    x = jnp.asarray(RNG.normal(size=(n_micro, MB, DIM)), jnp.float32)
    mesh = _mesh(n_stages)

    run = shard_map(
        lambda stacked, x: pipeline_apply(
            _stage_fn,
            jax.tree_util.tree_map(lambda a: a[0], stacked),
            x,
            axis_name="pp",
        ),
        mesh=mesh,
        in_specs=(P("pp"), P()),
        out_specs=P(),
    )
    g = jax.jit(jax.grad(lambda p, x: jnp.sum(run(p, x) ** 2)))(params, x)
    g_ref = jax.grad(
        lambda p, x: jnp.sum(pipeline_reference(_stage_fn, p, x) ** 2)
    )(params, x)
    for leaf, leaf_ref in zip(
        jax.tree_util.tree_leaves(g), jax.tree_util.tree_leaves(g_ref)
    ):
        np.testing.assert_allclose(
            np.asarray(leaf), np.asarray(leaf_ref), atol=1e-5, rtol=1e-5
        )


def test_pipeline_with_metric_counters():
    """Metric sufficient statistics computed on pipeline output inside the
    same jitted program equal the eager metric on the oracle output."""
    from torcheval_tpu.metrics.functional.classification.accuracy import (
        _multiclass_accuracy_update,
    )

    n_stages, n_micro = 4, 4
    params = _stacked_params(n_stages)
    x = jnp.asarray(RNG.normal(size=(n_micro, MB, DIM)), jnp.float32)
    targets = jnp.asarray(RNG.integers(0, DIM, (n_micro, MB)))
    mesh = _mesh(n_stages)

    @jax.jit
    @partial(
        shard_map, mesh=mesh, in_specs=(P("pp"), P(), P()), out_specs=P()
    )
    def run(stacked, x, targets):
        local = jax.tree_util.tree_map(lambda a: a[0], stacked)
        logits = pipeline_apply(_stage_fn, local, x, axis_name="pp")
        nc, nt = _multiclass_accuracy_update(
            logits.reshape(-1, DIM), targets.reshape(-1), "micro", None, 1
        )
        return jnp.stack([nc, nt])

    got = np.asarray(run(params, x, targets))
    oracle_logits = pipeline_reference(_stage_fn, params, x)
    nc, nt = _multiclass_accuracy_update(
        oracle_logits.reshape(-1, DIM), targets.reshape(-1), "micro", None, 1
    )
    assert got[1] == float(nt) == n_micro * MB
    assert got[0] == float(nc)
